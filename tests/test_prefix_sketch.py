"""Prefix-digest sketches (arks_tpu.prefix_sketch) + engine export.

Unit surface: bloom false-positive bound and determinism, exporter build
caching / membership invalidation / epoch bumping, conservative
text->token alignment, scoring determinism.  Integration surface: a real
tiny paged engine exports its tier membership via GET /v1/cache/sketch
and surfaces age/version metadata in /readiness.
"""

import json
import random
import urllib.request

import pytest

from arks_tpu import prefix_sketch as ps

PAGE = 16


def _rand_digests(rng, n):
    return [bytes(rng.getrandbits(8) for _ in range(20)) for _ in range(n)]


# ---------------------------------------------------------------------------
# Bloom filter
# ---------------------------------------------------------------------------

def test_bloom_false_positive_bound():
    """m=16384, k=4, n=512 members: theory says ~2e-4 FP; assert the
    observed rate over 20k absent probes stays under 0.5% — the bound the
    router's deepest-hit scoring budgets for."""
    rng = random.Random(7)
    members = _rand_digests(rng, 512)
    absent = _rand_digests(rng, 20000)
    b = ps.BloomSketch(16384, 4)
    for d in members:
        b.add(d)
    assert all(d in b for d in members), "bloom must never false-negative"
    fp = sum(1 for d in absent if d in b) / len(absent)
    assert fp < 0.005, f"observed false-positive rate {fp}"


def test_bloom_serialization_probes_identically():
    rng = random.Random(8)
    members = _rand_digests(rng, 64)
    probes = _rand_digests(rng, 512)
    b = ps.BloomSketch(4096, 4)
    for d in members:
        b.add(d)
    b2 = ps.BloomSketch.from_payload(json.loads(json.dumps(b.to_payload())))
    assert all((d in b) == (d in b2) for d in members + probes)


def test_chain_digests_shared_with_allocator():
    """paged.py re-exports the one hashing implementation — the router's
    token-domain probes and the engine's index keys must be bit-equal."""
    from arks_tpu.engine import paged
    ids = list(range(5, 70))
    assert paged.chain_digests(ids, PAGE, 4) == ps.chain_digests(ids, PAGE, 4)
    assert paged.iter_chain_digests is ps.iter_chain_digests


# ---------------------------------------------------------------------------
# Exporter
# ---------------------------------------------------------------------------

def _mk_exporter():
    return ps.SketchExporter(PAGE)


def test_build_is_cached_until_membership_changes():
    ex = _mk_exporter()
    rng = random.Random(9)
    dev = _rand_digests(rng, 8)
    host = _rand_digests(rng, 4)
    p1 = ex.build(dev, ("a", 1), host, 1)
    p2 = ex.build(dev, ("a", 1), host, 1)
    assert p1["version"] == p2["version"] == 1
    p3 = ex.build(dev + _rand_digests(rng, 1), ("a", 2), host, 1)
    assert p3["version"] == 2
    # Evicted members vanish from the summary.
    p4 = ex.build(dev[1:], ("a", 3), host, 1)
    bs = ps.BackendSketch.from_payload(p4)
    assert bs.score_chain([dev[0]], "token") == (0, 0, 0)
    assert bs.score_chain([dev[1]], "token") == (1, 0, 0)


def test_hit_counters_ride_every_response_uncached():
    ex = _mk_exporter()
    p1 = ex.build([], ("a", 1), [], 1, hit_tokens={"device": 1}, query_tokens=2)
    p2 = ex.build([], ("a", 1), [], 1, hit_tokens={"device": 9}, query_tokens=20)
    assert p1["version"] == p2["version"]
    assert p2["hit_tokens"]["device"] == 9 and p2["query_tokens"] == 20


def test_epoch_bump_invalidates_and_renames():
    ex = _mk_exporter()
    p1 = ex.build([], ("a", 1), [], 1)
    e1 = p1["epoch"]
    ex.bump_epoch()
    p2 = ex.build([], ("a", 1), [], 1)
    assert p2["epoch"] != e1
    assert p2["version"] > p1["version"]


def test_scoring_is_deterministic_and_tier_split():
    ex = _mk_exporter()
    rng = random.Random(10)
    chain = _rand_digests(rng, 6)
    # Blocks 0-2 device-resident, 3-4 host-resident, 5 nowhere.
    payload = ex.build(chain[:3], ("a", 1), chain[3:5], 1)
    bs = ps.BackendSketch.from_payload(payload)
    for _ in range(3):
        assert bs.score_chain(chain, "token") == (3, 2, 0)
    # A hole in the device run stops tier-0 counting there; the host walk
    # continues from the miss point only if resident.
    holey = [chain[0], _rand_digests(rng, 1)[0]] + chain[1:]
    dev, host, _disk = bs.score_chain(holey, "token")
    assert dev == 1 and host == 0


def test_text_alignment_rounds_token_depth_up():
    """Text block j maps to the token depth that PROVABLY covers it:
    claimed coverage must never exceed the proportional token estimate
    rounded up to a page boundary."""
    ex = _mk_exporter()
    text = "x" * (ex.text_chars * 3)          # 3 full text blocks
    ids = list(range(4 * PAGE))               # 4 full token pages
    ex.link(text, ids)
    toks = ps.chain_digests(ids, PAGE, 4)
    tds = list(ps.iter_text_digests(text, ex.text_chars))
    # Text block 0 covers 1/3 of the text -> ceil(4/3 pages)=2 pages; the
    # sketch must demand token depth 2 resident before advertising it.
    payload = ex.build(toks[:1], ("a", 1), [], 1)
    bs = ps.BackendSketch.from_payload(payload)
    assert bs.score_chain(tds, "text") == (0, 0, 0)
    payload = ex.build(toks[:2], ("a", 2), [], 1)
    bs = ps.BackendSketch.from_payload(payload)
    assert bs.score_chain(tds, "text")[0] == 1
    payload = ex.build(toks, ("a", 3), [], 1)
    bs = ps.BackendSketch.from_payload(payload)
    assert bs.score_chain(tds, "text")[0] == 3


def test_link_ledger_is_bounded(monkeypatch):
    monkeypatch.setenv("ARKS_ROUTER_SKETCH_LINKS", "4")
    ex = ps.SketchExporter(PAGE)
    for i in range(10):
        ex.link(f"{i:03d}" + "y" * ex.text_chars, list(range(PAGE)))
    assert len(ex._links) <= 4


def test_canonical_prompt_text_rules():
    assert ps.canonical_prompt_text({"prompt": "abc"}) == "abc"
    assert ps.canonical_prompt_text({"prompt": [1, 2, 3]}) is None
    assert ps.canonical_prompt_text(
        {"messages": [{"role": "u", "content": "a"},
                      {"role": "a", "content": "b"}]}) == "a\x00b"
    # Unknown content shape stops the scan — later turns never leak in.
    assert ps.canonical_prompt_text(
        {"messages": [{"role": "u", "content": {"x": 1}},
                      {"role": "a", "content": "b"}]}) is None
    assert ps.canonical_prompt_text(
        {"messages": [{"role": "u", "content": [
            {"type": "text", "text": "hi"}]}]}) == "hi"


# ---------------------------------------------------------------------------
# Engine + server integration
# ---------------------------------------------------------------------------

@pytest.fixture()
def sketch_server(monkeypatch):
    from arks_tpu.engine import (EngineConfig, InferenceEngine, Request,
                                 SamplingParams)
    from arks_tpu.engine.tokenizer import ByteTokenizer
    from arks_tpu.models import get_config
    from arks_tpu.server import OpenAIServer

    monkeypatch.setenv("ARKS_PREFIX_HOST_MB", "64")
    # ByteTokenizer is 1 char = 1 token and max_cache_len is 64: shrink
    # the text block so a full block fits in one request.
    monkeypatch.setenv("ARKS_ROUTER_SKETCH_CHARS", "16")
    cfg = get_config("tiny")
    ecfg = EngineConfig(model="tiny", num_slots=2, max_cache_len=64,
                        prefill_buckets=(8, 16, 32), steps_per_dispatch=4,
                        prefill_chunk=PAGE, kv_layout="paged",
                        prefix_cache_mb=0)
    eng = InferenceEngine(cfg, ecfg, ByteTokenizer())
    eng.start()
    srv = OpenAIServer(eng, served_model_name="tiny-sk", host="127.0.0.1",
                       port=0)
    srv.start(background=True)
    yield cfg, eng, srv, Request, SamplingParams
    srv.stop()
    eng.stop()


def _get(port, path):
    with urllib.request.urlopen(f"http://127.0.0.1:{port}{path}",
                                timeout=60) as r:
        return json.load(r)


def test_engine_exports_resident_chain(sketch_server):
    cfg, eng, srv, Request, SamplingParams = sketch_server
    warm = [int(x) % cfg.vocab_size for x in range(3, 36)]  # 2 pages + tail
    req = Request("sk1", warm, SamplingParams(max_tokens=4, temperature=0.0,
                                              ignore_eos=True))
    eng.add_request(req)
    while True:
        if req.outputs.get(timeout=120).finished:
            break
    payload = _get(srv.port, "/v1/cache/sketch")
    assert payload["enabled"] and payload["page_tokens"] == PAGE
    bs = ps.BackendSketch.from_payload(payload)
    digs = ps.chain_digests(warm, PAGE, 2)
    dev, host, _disk = bs.score_chain(digs, "token")
    assert dev + host == 2, "the warm prompt's pages are resident somewhere"
    # Version metadata is stable while membership is.
    again = _get(srv.port, "/v1/cache/sketch")
    assert again["version"] == payload["version"]
    assert again["epoch"] == payload["epoch"]


def test_readiness_carries_sketch_metadata(sketch_server):
    _, _, srv, _, _ = sketch_server
    ready = _get(srv.port, "/readiness")
    assert ready["status"] == "ready"
    meta = ready["sketch"]
    assert meta["enabled"] and meta["version"] >= 1
    assert meta["age_s"] >= 0.0 and "." in meta["epoch"]


def test_server_links_text_prompts(sketch_server):
    """POSTing a text completion records the text->token alignment, so a
    text-domain probe scores the resident chain without any tokenizer on
    the probing side."""
    cfg, eng, srv, _, _ = sketch_server
    text = "the quick brown fox jumps over the lazy dog, twic"  # 49 chars
    body = json.dumps({"model": "tiny-sk", "prompt": text, "max_tokens": 2,
                       "temperature": 0, "ignore_eos": True}).encode()
    req = urllib.request.Request(
        f"http://127.0.0.1:{srv.port}/v1/completions", data=body,
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=120) as r:
        json.load(r)
    payload = _get(srv.port, "/v1/cache/sketch")
    bs = ps.BackendSketch.from_payload(payload)
    chars = payload["text_chars"]
    tds = list(ps.iter_text_digests(text, chars))
    assert tds, "test text shorter than a text block"
    dev, host, _disk = bs.score_chain(tds, "text")
    assert dev + host >= 1, "text-domain membership never surfaced"
