"""Engine-level tests: continuous batching, stop handling, streaming."""

import queue

import pytest

from arks_tpu.engine import EngineConfig, InferenceEngine, Request, SamplingParams
from arks_tpu.engine.tokenizer import ByteTokenizer
from arks_tpu.models import get_config


@pytest.fixture(scope="module")
def engine():
    cfg = get_config("tiny")
    ecfg = EngineConfig(model="tiny", num_slots=2, max_cache_len=64,
                        prefill_buckets=(8, 16, 32), steps_per_dispatch=4)
    eng = InferenceEngine(cfg, ecfg, ByteTokenizer())
    yield eng


def _collect(req: Request, timeout=60):
    ids, finished = [], None
    while True:
        out = req.outputs.get(timeout=timeout)
        ids.extend(out.token_ids)
        if out.finished:
            finished = out
            break
    return ids, finished


def _drive(engine, n_steps=200):
    for _ in range(n_steps):
        engine.step(block_s=0.01)
        if engine.num_running == 0 and engine._queue.empty():
            break


def test_single_request_greedy(engine):
    req = Request("r1", [5, 6, 7], SamplingParams(max_tokens=8, temperature=0.0,
                                                  ignore_eos=True))
    engine.add_request(req)
    _drive(engine)
    ids, fin = _collect(req)
    assert len(ids) == 8
    assert fin.finish_reason == "length"
    assert fin.num_prompt_tokens == 3

    # Determinism: same request again gives the same tokens.
    req2 = Request("r2", [5, 6, 7], SamplingParams(max_tokens=8, temperature=0.0,
                                                   ignore_eos=True))
    engine.add_request(req2)
    _drive(engine)
    ids2, _ = _collect(req2)
    assert ids2 == ids


def test_more_requests_than_slots(engine):
    reqs = [Request(f"m{i}", [10 + i, 20], SamplingParams(max_tokens=5, temperature=0.0,
                                                          ignore_eos=True))
            for i in range(5)]
    for r in reqs:
        engine.add_request(r)
    _drive(engine, 400)
    for r in reqs:
        ids, fin = _collect(r)
        assert fin.finished and len(ids) == 5


def test_stop_token(engine):
    # Force a stop token that greedy decoding actually produces: run once to
    # learn the first generated token, then use it as the stop token.
    probe = Request("p", [9, 9], SamplingParams(max_tokens=3, temperature=0.0,
                                                ignore_eos=True))
    engine.add_request(probe)
    _drive(engine)
    probe_ids, _ = _collect(probe)

    stop = probe_ids[1]
    req = Request("s", [9, 9], SamplingParams(max_tokens=10, temperature=0.0,
                                              stop_token_ids=(stop,), ignore_eos=True))
    engine.add_request(req)
    _drive(engine)
    ids, fin = _collect(req)
    assert fin.finish_reason == "stop"
    assert stop not in ids
    assert ids == probe_ids[:1]


def test_sampled_request_valid(engine):
    req = Request("t", [1, 2, 3], SamplingParams(max_tokens=6, temperature=0.8,
                                                 top_p=0.9, top_k=40, seed=42,
                                                 ignore_eos=True))
    engine.add_request(req)
    _drive(engine)
    ids, fin = _collect(req)
    assert len(ids) == 6
    assert all(0 <= t < get_config("tiny").vocab_size for t in ids)

    # Same seed → same sample path.
    req2 = Request("t2", [1, 2, 3], SamplingParams(max_tokens=6, temperature=0.8,
                                                   top_p=0.9, top_k=40, seed=42,
                                                   ignore_eos=True))
    engine.add_request(req2)
    _drive(engine)
    ids2, _ = _collect(req2)
    assert ids2 == ids


def test_long_prompt_truncated(engine):
    # 57 tokens fits the implicit max_cache_len bucket (64) minus headroom.
    req = Request("lp", list(range(3, 60)), SamplingParams(max_tokens=3, temperature=0.0,
                                                           ignore_eos=True))
    engine.add_request(req)
    _drive(engine)
    ids, fin = _collect(req)
    assert fin.finished and len(ids) == 3
    assert fin.num_prompt_tokens == 57

    # 100 tokens exceeds the cache: truncated to max_cache_len - K - 1, and
    # generation still proceeds.
    req2 = Request("lp2", list(range(3, 103)), SamplingParams(max_tokens=3, temperature=0.0,
                                                              ignore_eos=True))
    engine.add_request(req2)
    _drive(engine)
    ids2, fin2 = _collect(req2)
    assert fin2.finished and len(ids2) >= 1
    assert fin2.num_prompt_tokens == 64 - 4 - 1


def test_metrics_populated(engine):
    text = engine.metrics.registry.render()
    assert "prompt_tokens_total" in text
    assert "generation_tokens_total" in text
    assert "time_to_first_token_seconds_bucket" in text


def test_cache_len_alignment_rounds_up_for_pallas(monkeypatch):
    """A misaligned --max-model-len must self-correct at startup, not raise
    deep inside the first decode dispatch (kernel DMA tiling constraints)."""
    monkeypatch.setenv("ARKS_ATTN_IMPL", "pallas")
    ecfg = EngineConfig(model="tiny", max_cache_len=1000, kv_cache_dtype="int8")
    ecfg.align_cache_len()
    assert ecfg.max_cache_len == 1024  # multiple of 256 covers all kernels
    ecfg2 = EngineConfig(model="tiny", max_cache_len=100, kv_cache_dtype="int8")
    ecfg2.align_cache_len()
    assert ecfg2.max_cache_len == 128  # int8 scale tile below 256
    ecfg3 = EngineConfig(model="tiny", max_cache_len=50, kv_cache_dtype="bf16")
    ecfg3.align_cache_len()
    assert ecfg3.max_cache_len == 64  # bf16 update tile


def test_cache_len_untouched_on_xla_path(monkeypatch):
    monkeypatch.setenv("ARKS_ATTN_IMPL", "xla")
    ecfg = EngineConfig(model="tiny", max_cache_len=1000, kv_cache_dtype="int8")
    ecfg.align_cache_len()
    assert ecfg.max_cache_len == 1000


def test_mesh_plan_validation_raises_value_error():
    from arks_tpu.parallel.mesh import resolve_plan
    with pytest.raises(ValueError):
        resolve_plan(8, tensor_parallel=3)
    with pytest.raises(ValueError):
        resolve_plan(8, context_parallel=3)
    with pytest.raises(ValueError):
        resolve_plan(8, data_parallel=3)
