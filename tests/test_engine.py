"""Engine-level tests: continuous batching, stop handling, streaming."""

import queue

import pytest

from arks_tpu.engine import EngineConfig, InferenceEngine, Request, SamplingParams
from arks_tpu.engine.tokenizer import ByteTokenizer
from arks_tpu.models import get_config


@pytest.fixture(scope="module")
def engine():
    cfg = get_config("tiny")
    ecfg = EngineConfig(model="tiny", num_slots=2, max_cache_len=64,
                        prefill_buckets=(8, 16, 32), steps_per_dispatch=4)
    eng = InferenceEngine(cfg, ecfg, ByteTokenizer())
    yield eng


def _collect(req: Request, timeout=60):
    ids, finished = [], None
    while True:
        out = req.outputs.get(timeout=timeout)
        ids.extend(out.token_ids)
        if out.finished:
            finished = out
            break
    return ids, finished


def _drive(engine, n_steps=200):
    for _ in range(n_steps):
        engine.step(block_s=0.01)
        if (engine.num_running == 0 and engine._queue.empty()
                and not engine._prefilling):
            break


def test_single_request_greedy(engine):
    req = Request("r1", [5, 6, 7], SamplingParams(max_tokens=8, temperature=0.0,
                                                  ignore_eos=True))
    engine.add_request(req)
    _drive(engine)
    ids, fin = _collect(req)
    assert len(ids) == 8
    assert fin.finish_reason == "length"
    assert fin.num_prompt_tokens == 3

    # Determinism: same request again gives the same tokens.
    req2 = Request("r2", [5, 6, 7], SamplingParams(max_tokens=8, temperature=0.0,
                                                   ignore_eos=True))
    engine.add_request(req2)
    _drive(engine)
    ids2, _ = _collect(req2)
    assert ids2 == ids


def test_more_requests_than_slots(engine):
    reqs = [Request(f"m{i}", [10 + i, 20], SamplingParams(max_tokens=5, temperature=0.0,
                                                          ignore_eos=True))
            for i in range(5)]
    for r in reqs:
        engine.add_request(r)
    _drive(engine, 400)
    for r in reqs:
        ids, fin = _collect(r)
        assert fin.finished and len(ids) == 5


def test_stop_token(engine):
    # Force a stop token that greedy decoding actually produces: run once to
    # learn the first generated token, then use it as the stop token.
    probe = Request("p", [9, 9], SamplingParams(max_tokens=3, temperature=0.0,
                                                ignore_eos=True))
    engine.add_request(probe)
    _drive(engine)
    probe_ids, _ = _collect(probe)

    stop = probe_ids[1]
    req = Request("s", [9, 9], SamplingParams(max_tokens=10, temperature=0.0,
                                              stop_token_ids=(stop,), ignore_eos=True))
    engine.add_request(req)
    _drive(engine)
    ids, fin = _collect(req)
    assert fin.finish_reason == "stop"
    assert stop not in ids
    assert ids == probe_ids[:1]


def test_sampled_request_valid(engine):
    req = Request("t", [1, 2, 3], SamplingParams(max_tokens=6, temperature=0.8,
                                                 top_p=0.9, top_k=40, seed=42,
                                                 ignore_eos=True))
    engine.add_request(req)
    _drive(engine)
    ids, fin = _collect(req)
    assert len(ids) == 6
    assert all(0 <= t < get_config("tiny").vocab_size for t in ids)

    # Same seed → same sample path.
    req2 = Request("t2", [1, 2, 3], SamplingParams(max_tokens=6, temperature=0.8,
                                                   top_p=0.9, top_k=40, seed=42,
                                                   ignore_eos=True))
    engine.add_request(req2)
    _drive(engine)
    ids2, _ = _collect(req2)
    assert ids2 == ids


def test_seeded_sampling_independent_of_scheduler_timing(monkeypatch):
    """A seeded sampled request produces the same tokens whether it is
    admitted alone or while another request is mid-decode with its
    admission resolution DELAYED.  With deferred resolution, decode
    dispatches land between a slot's admit program (which seeds its PRNG
    key) and its registration — the fused loop's active mask must freeze
    pending/free slots' keys or the stream would depend on scheduler
    timing.  (CPU resolves admissions near-instantly, so the deferral
    window is forced by holding back the drain for a few steps — the
    shape a slow tunneled device produces naturally.)"""
    from arks_tpu.engine.engine import InferenceEngine as IE
    cfg = get_config("tiny")

    orig_drain = IE._drain_ready_admits

    def run(with_load, delay_steps):
        calls = {"n": 0}

        def delayed(self, force_one=False):
            # Pretend the admit program is still in flight for a few
            # scheduler steps; decode dispatches keep flowing meanwhile.
            calls["n"] += 1
            if calls["n"] <= delay_steps and self._slots:
                return False
            return orig_drain(self, force_one=force_one)

        monkeypatch.setattr(IE, "_drain_ready_admits", delayed)
        ecfg = EngineConfig(model="tiny", num_slots=2, max_cache_len=64,
                            prefill_buckets=(8, 16, 32),
                            steps_per_dispatch=4)
        eng = InferenceEngine(cfg, ecfg, ByteTokenizer())
        eng.start()
        try:
            if with_load:
                # A long-running greedy request keeps decode dispatches
                # flowing while the sampled request's admission pends.
                load = Request("load", [9, 9, 9], SamplingParams(
                    max_tokens=40, temperature=0.0, ignore_eos=True))
                eng.add_request(load)
                load.outputs.get(timeout=60)  # wait until it is decoding
                calls["n"] = 0  # arm the delay for the sampled admission
            req = Request("s", [1, 2, 3], SamplingParams(
                max_tokens=6, temperature=0.8, top_p=0.9, top_k=40,
                seed=42, ignore_eos=True))
            eng.add_request(req)
            ids, _ = _collect(req)
            if with_load:
                _collect(load)
            return ids
        finally:
            eng.stop()

    assert run(True, delay_steps=6) == run(False, delay_steps=0)


def test_long_prompt_chunked_prefill(engine):
    # 57 tokens exceeds the largest one-shot bucket (32) but fits the cache
    # (64 - 4 - 1 = 59 usable): served via chunked prefill.
    req = Request("lp", list(range(3, 60)), SamplingParams(max_tokens=3, temperature=0.0,
                                                           ignore_eos=True))
    engine.add_request(req)
    _drive(engine)
    ids, fin = _collect(req)
    assert fin.finished and len(ids) == 3
    assert fin.num_prompt_tokens == 57


def test_oversize_prompt_rejected_not_truncated(engine):
    # 100 tokens exceeds the usable window: the request is REJECTED with a
    # machine-readable error (silent truncation would corrupt long-context
    # results and billing) — OpenAI servers surface this as HTTP 400.
    req = Request("lp2", list(range(3, 103)), SamplingParams(max_tokens=3, temperature=0.0,
                                                             ignore_eos=True))
    engine.add_request(req)
    _drive(engine)
    ids, fin = _collect(req)
    assert fin.finished and not ids
    assert fin.finish_reason == "error"
    assert fin.error == "context_length_exceeded"
    assert fin.num_prompt_tokens == 100


def test_chunked_prefill_matches_one_shot():
    """A prompt served via chunked prefill must produce the same greedy
    tokens as the same prompt through one-shot prefill (same math,
    blockwise — only fp reassociation differs)."""
    cfg = get_config("tiny")
    prompt = [int(x) % cfg.vocab_size for x in range(7, 55)]  # 48 tokens

    def run(prefill_buckets, prefill_chunk):
        ecfg = EngineConfig(model="tiny", num_slots=2, max_cache_len=64,
                            prefill_buckets=prefill_buckets,
                            steps_per_dispatch=4, prefill_chunk=prefill_chunk)
        eng = InferenceEngine(cfg, ecfg, ByteTokenizer())
        req = Request("c", prompt, SamplingParams(max_tokens=6, temperature=0.0,
                                                  ignore_eos=True, seed=7))
        eng.add_request(req)
        _drive(eng)
        ids, fin = _collect(req)
        return ids, fin

    # One-shot: bucket 64 covers the prompt.  Chunked: largest bucket is 16,
    # so the 48-token prompt runs as 16-token chunks.
    ids_one, fin_one = run((16, 32, 64), None)
    ids_chunk, fin_chunk = run((8, 16), 16)
    assert fin_chunk.num_prompt_tokens == fin_one.num_prompt_tokens == 48
    assert ids_chunk == ids_one


def test_decode_flows_during_chunked_prefill():
    """Decode slots must keep producing tokens while a long prompt is being
    chunk-prefilled — the whole point of chunking (one chunk per scheduler
    step, decode dispatch in the same step)."""
    cfg = get_config("tiny")
    ecfg = EngineConfig(model="tiny", num_slots=2, max_cache_len=64,
                        prefill_buckets=(8,), steps_per_dispatch=1,
                        prefill_chunk=8)
    eng = InferenceEngine(cfg, ecfg, ByteTokenizer())

    # Short request occupies a decode slot first.
    short = Request("s", [5, 6], SamplingParams(max_tokens=40, temperature=0.0,
                                                ignore_eos=True))
    eng.add_request(short)
    eng.step(block_s=0.01)  # admits + first decode
    # Long prompt: 48 tokens = 6 chunks of 8.
    long_req = Request("l", [int(x) % cfg.vocab_size for x in range(3, 51)],
                       SamplingParams(max_tokens=2, temperature=0.0,
                                      ignore_eos=True))
    eng.add_request(long_req)

    # Step until the long prompt's first token appears; the short request
    # must have produced tokens in the SAME window (interleaved).
    short_tokens_during = 0
    for _ in range(200):
        eng.step(block_s=0.01)
        if eng._prefilling:
            # Chunked prefill still in progress — decode output must flow.
            while not short.outputs.empty():
                short_tokens_during += len(short.outputs.get().token_ids)
        if long_req.outputs.qsize() > 0:
            break
    assert short_tokens_during > 0, "decode stalled during chunked prefill"
    _drive(eng)
    ids, fin = _collect(long_req)
    assert fin.finished and fin.num_prompt_tokens == 48


def test_metrics_populated(engine):
    text = engine.metrics.registry.render()
    assert "prompt_tokens_total" in text
    assert "generation_tokens_total" in text
    assert "time_to_first_token_seconds_bucket" in text


def test_resolved_config_surfaced(engine):
    """The RESOLVED engine configuration (auto decisions included) rides
    /metrics as an _info gauge and the engine object, so bench_serving and
    dashboards can tell which perf envelope produced a number."""
    rc = engine.resolved_config
    assert rc["kv_layout"] in ("paged", "slot")
    assert rc["decode_impl"] in ("pallas", "xla")
    assert rc["pad_head"] in ("true", "false")
    assert rc["overlap"] in ("true", "false")
    text = engine.metrics.registry.render()
    assert "engine_config_info{" in text
    assert f'kv_layout="{rc["kv_layout"]}"' in text
    assert f'decode_impl="{rc["decode_impl"]}"' in text
    # The pure device-wait counter rides every decode resolve (the
    # overlap-mode-trustworthy signal bench_serving reports).  Drive one
    # tiny request HERE so a sample line exists even when this test runs
    # alone, then assert a non-comment line (comment lines start '# ').
    req = Request("rc-cfg", [5, 6, 7], SamplingParams(
        max_tokens=3, temperature=0.0, ignore_eos=True))
    engine.add_request(req)
    _drive(engine)
    text = engine.metrics.registry.render()
    # Split by mode since the pipelined scheduler: either family proves
    # the counter rides the resolves.
    assert ('decode_resolve_wait_seconds_total{mode="sequential"}' in text
            or 'decode_resolve_wait_seconds_total{mode="pipelined"}' in text)
    assert f'pipeline_depth="{rc["pipeline_depth"]}"' in text


def test_cache_len_alignment_rounds_up_for_pallas(monkeypatch):
    """A misaligned --max-model-len must self-correct at startup, not raise
    deep inside the first decode dispatch (kernel DMA tiling constraints)."""
    monkeypatch.setenv("ARKS_ATTN_IMPL", "pallas")
    ecfg = EngineConfig(model="tiny", max_cache_len=1000, kv_cache_dtype="int8")
    ecfg.align_cache_len()
    assert ecfg.max_cache_len == 1024  # multiple of 256 covers all kernels
    ecfg2 = EngineConfig(model="tiny", max_cache_len=100, kv_cache_dtype="int8")
    ecfg2.align_cache_len()
    assert ecfg2.max_cache_len == 128  # int8 scale tile below 256
    ecfg3 = EngineConfig(model="tiny", max_cache_len=50, kv_cache_dtype="bf16")
    ecfg3.align_cache_len()
    assert ecfg3.max_cache_len == 64  # bf16 update tile


def test_cache_len_untouched_on_xla_path(monkeypatch):
    monkeypatch.setenv("ARKS_ATTN_IMPL", "xla")
    ecfg = EngineConfig(model="tiny", max_cache_len=1000, kv_cache_dtype="int8")
    ecfg.align_cache_len()
    assert ecfg.max_cache_len == 1000


def test_mesh_plan_validation_raises_value_error():
    from arks_tpu.parallel.mesh import resolve_plan
    with pytest.raises(ValueError):
        resolve_plan(8, tensor_parallel=3)
    with pytest.raises(ValueError):
        resolve_plan(8, context_parallel=3)
    with pytest.raises(ValueError):
        resolve_plan(8, data_parallel=3)


def test_frequency_penalty_reduces_repetition():
    """End to end through the engine: the tiny greedy model repeats itself;
    a frequency penalty must strictly reduce the max token repeat count,
    deterministically."""
    from collections import Counter

    def run(presence, frequency):
        cfg = get_config("tiny")
        ecfg = EngineConfig(model="tiny", num_slots=2, max_cache_len=128,
                            prefill_buckets=(16, 32), steps_per_dispatch=4,
                            prefix_cache_mb=0)
        eng = InferenceEngine(cfg, ecfg, ByteTokenizer())
        req = Request("p", [5, 6, 7], SamplingParams(
            max_tokens=60, temperature=0.0, ignore_eos=True,
            presence_penalty=presence, frequency_penalty=frequency))
        eng.add_request(req)
        _drive(eng, n_steps=400)
        ids, _ = _collect(req)
        return ids

    plain = run(0.0, 0.0)
    penalized = run(0.5, 1.5)
    top_plain = Counter(plain).most_common(1)[0][1]
    top_pen = Counter(penalized).most_common(1)[0][1]
    assert top_pen < top_plain, (top_plain, top_pen)
    assert len(set(penalized)) > len(set(plain))
    # Deterministic (greedy + penalties is still deterministic).
    assert run(0.5, 1.5) == penalized


def test_overlap_decode_matches_sequential(monkeypatch):
    """ARKS_OVERLAP_DECODE=1 (the TPU default: decode issued async,
    admissions overlap the in-flight dispatch) must produce byte-identical
    outputs to the sequential order, including slot churn and prefix
    sharing."""
    from arks_tpu.engine import EngineConfig, InferenceEngine
    from arks_tpu.engine.tokenizer import ByteTokenizer
    from arks_tpu.engine.types import Request, SamplingParams
    from arks_tpu.models import get_config

    cfg = get_config("tiny")
    prompts = [[3] * 20, [3] * 20, [5, 6, 7], [9] * 33, [4, 8]]

    def run(overlap):
        monkeypatch.setenv("ARKS_OVERLAP_DECODE", overlap)
        ecfg = EngineConfig(model="tiny", num_slots=2, max_cache_len=64,
                            prefill_buckets=(8, 16, 32),
                            steps_per_dispatch=4, prefill_chunk=16,
                            kv_layout="paged")
        eng = InferenceEngine(cfg, ecfg, ByteTokenizer())
        assert eng._overlap == (overlap == "1")
        eng.start()
        outs = []
        try:
            reqs = [Request(request_id=f"o{i}", prompt_ids=list(p),
                            params=SamplingParams(max_tokens=6,
                                                  temperature=0.0,
                                                  ignore_eos=True))
                    for i, p in enumerate(prompts)]
            for r in reqs:  # burst: more requests than slots -> churn
                eng.add_request(r)
            for r in reqs:
                toks = []
                while True:
                    o = r.outputs.get(timeout=120)
                    toks.extend(o.token_ids)
                    if o.finished:
                        break
                outs.append(toks)
        finally:
            eng.stop()
        return outs

    assert run("1") == run("0")


def test_admit_batch_sizes_env_override(monkeypatch):
    """ARKS_ADMIT_BATCH_SIZES tunes the fused-admission fill sizes without
    a code change (the serving sweep's knob): parsed, normalized
    descending, floor of 1 enforced, surfaced in resolved config, and the
    engine still serves correctly with a deeper ladder."""
    monkeypatch.setenv("ARKS_ADMIT_BATCH_SIZES", "2,16,4")
    cfg = get_config("tiny")
    ecfg = EngineConfig(model="tiny", num_slots=4, max_cache_len=64,
                        prefill_buckets=(8, 16, 32), steps_per_dispatch=4)
    eng = InferenceEngine(cfg, ecfg, ByteTokenizer())
    assert eng._admit_sizes == (16, 4, 2, 1)
    assert eng.resolved_config["admit_batch_sizes"] == "16,4,2,1"
    reqs = [Request(f"ab{i}", [3 + i, 9, 11], SamplingParams(
        max_tokens=4, temperature=0.0, ignore_eos=True)) for i in range(3)]
    for r in reqs:
        eng.add_request(r)
    _drive(eng)
    for r in reqs:
        ids, fin = _collect(r)
        assert len(ids) == 4 and fin.finished


def test_priority_admission_order():
    """Waiting requests admit in priority order (lower value first, FIFO
    within a priority); running slots are never preempted.  Driven
    manually (no engine thread) so all three contenders are queued before
    any admission step — the ordering is then purely the queue's."""
    cfg = get_config("tiny")
    ecfg = EngineConfig(model="tiny", num_slots=1, max_cache_len=64,
                        prefill_buckets=(8,), steps_per_dispatch=2)
    eng = InferenceEngine(cfg, ecfg, ByteTokenizer())
    hold = Request("hold", [3, 4], SamplingParams(
        max_tokens=30, temperature=0.0, ignore_eos=True))
    eng.add_request(hold)
    for _ in range(50):
        eng.step(block_s=0.01)
        if eng.num_running == 1 and eng._queue.empty():
            break
    assert eng.num_running == 1  # hold occupies the single slot

    def submit(rid, prio):
        r = Request(rid, [5, 6], SamplingParams(
            max_tokens=2, temperature=0.0, ignore_eos=True, priority=prio))
        eng.add_request(r)
        return r

    low1 = submit("low-1", 5)
    low2 = submit("low-2", 5)
    high = submit("high", -1)
    reqs = {"low-1": low1, "low-2": low2, "high": high}
    order, pending = [], set(reqs)
    for _ in range(600):
        eng.step(block_s=0.01)
        for name in list(pending):
            try:
                out = reqs[name].outputs.get_nowait()
            except queue.Empty:
                continue
            if out.finished:
                order.append(name)
                pending.discard(name)
        if not pending:
            break
    # One slot: completions happen in admission order.
    assert order == ["high", "low-1", "low-2"]


def test_abort_after_finish_does_not_linger(engine):
    """An abort that loses the race with _finish (or targets a request id
    that never existed) must not sit in the abort set forever — the idle
    scheduler purges it (regression: the purge used to run only while
    slots existed, so an idle engine leaked every late abort)."""
    req = Request("late-abort", [5, 6], SamplingParams(
        max_tokens=2, temperature=0.0, ignore_eos=True))
    engine.add_request(req)
    _drive(engine)
    _, out = _collect(req)
    assert out.finish_reason == "length"
    engine.abort("late-abort")      # after _finish: nothing to abort
    engine.abort("never-existed")   # garbage id
    for _ in range(5):
        engine.step(block_s=0.01)   # idle steps run the purge
    with engine._abort_lock:
        assert not engine._aborted, "stale abort ids leaked"
