"""Tenant-fair admission queue (arks_tpu.engine.fairqueue) unit tests.

The invariance contracts the module doc promises are the tests here:
single-tenant order is byte-for-byte the old tier-FIFO order, WDRR
interleaves tenants by token bandwidth (weighted), tiers stay strict,
the urgent lane (priority < 0) preempts everything and dodges bounds,
bounded puts raise typed QueueFullError with a usable Retry-After, and
aging promotes per-tenant in arrival order.  tenancy helpers (weight
parsing, bounded labels) ride along — same PR, same contracts.
"""

import queue as stdq
import time

import pytest

from arks_tpu import tenancy
from arks_tpu.engine.fairqueue import FairQueue, QueueFullError, request_cost
from arks_tpu.engine.types import Request, SamplingParams


def _req(rid, tenant=None, prompt=3, max_tokens=2, priority=0):
    return Request(rid, [7] * prompt,
                   SamplingParams(max_tokens=max_tokens, priority=priority),
                   tenant=tenant)


def _q(**kw):
    kw.setdefault("fair", True)
    kw.setdefault("quantum", 1)
    kw.setdefault("weights", {})
    kw.setdefault("max_total", 0)
    kw.setdefault("max_tenant", 0)
    return FairQueue(**kw)


def _drain(q):
    out = []
    while not q.empty():
        out.append(q.get_nowait()[2].request_id)
    return out


# ---------------------------------------------------------------- ordering


def test_single_tenant_keeps_tier_then_fifo_order():
    """With one tenant the fair queue must reproduce the old
    PriorityQueue schedule exactly (untenanted deployments unchanged)."""
    q = _q()
    items = [(1, 0, _req("r0", priority=1)), (0, 1, _req("r1")),
             (2, 2, _req("r2", priority=2)), (0, 3, _req("r3")),
             (1, 4, _req("r4", priority=1))]
    for it in items:
        q.put(it)
    assert _drain(q) == ["r1", "r3", "r0", "r4", "r2"]


def test_two_tenants_interleave_within_a_tier():
    q = _q()
    for i in range(3):
        q.put((0, 2 * i, _req(f"a{i}", tenant="ns/a")))
        q.put((0, 2 * i + 1, _req(f"b{i}", tenant="ns/b")))
    order = _drain(q)
    # Each tenant's own order is FIFO, and service interleaves: DRR
    # guarantees bandwidth fairness (both tenants appear in every window
    # of three picks), not strict alternation.
    assert [r for r in order if r.startswith("a")] == ["a0", "a1", "a2"]
    assert [r for r in order if r.startswith("b")] == ["b0", "b1", "b2"]
    for w in (order[i:i + 3] for i in range(len(order) - 2)):
        assert len({r[0] for r in w}) == 2, order


def test_flood_does_not_starve_the_other_tenant():
    q = _q()
    for i in range(50):
        q.put((0, i, _req(f"a{i}", tenant="ns/flood")))
    q.put((0, 50, _req("v0", tenant="ns/victim")))
    order = _drain(q)
    # The victim is served within a couple of picks, not after the flood.
    assert order.index("v0") <= 2, order


def test_weights_bias_token_bandwidth():
    q = _q(weights={"ns/a": 2.0})
    for i in range(30):
        q.put((0, 2 * i, _req(f"a{i}", tenant="ns/a")))
        q.put((0, 2 * i + 1, _req(f"b{i}", tenant="ns/b")))
    first = [q.get_nowait()[2].request_id for _ in range(18)]
    n_a = sum(1 for r in first if r.startswith("a"))
    # weight 2 vs 1 with equal request costs: ~2/3 of picks go to a.
    assert 10 <= n_a <= 14, first


def test_tiers_stay_strict_across_tenants():
    q = _q()
    q.put((1, 0, _req("slow-a", tenant="ns/a", priority=1)))
    q.put((0, 1, _req("fast-b", tenant="ns/b")))
    q.put((1, 2, _req("slow-b", tenant="ns/b", priority=1)))
    q.put((0, 3, _req("fast-a", tenant="ns/a")))
    order = _drain(q)
    assert set(order[:2]) == {"fast-b", "fast-a"}
    assert set(order[2:]) == {"slow-a", "slow-b"}


def test_urgent_lane_served_first_and_exempt_from_bounds():
    q = _q(max_total=1)
    q.put((0, 0, _req("normal")), bounded=True)
    # Replayers carry priority - 2**20: never bounded, always first.
    q.put((-2 ** 20, 1, _req("replay")), bounded=True)
    assert q.get_nowait()[2].request_id == "replay"
    assert q.get_nowait()[2].request_id == "normal"


# ------------------------------------------------------------------ bounds


def test_total_bound_raises_scope_queue():
    q = _q(max_total=2)
    q.put((0, 0, _req("r0", tenant="ns/a")), bounded=True)
    q.put((0, 1, _req("r1", tenant="ns/b")), bounded=True)
    with pytest.raises(QueueFullError) as ei:
        q.put((0, 2, _req("r2", tenant="ns/c")), bounded=True)
    assert ei.value.scope == "queue"
    assert ei.value.retry_after >= 1
    assert q.qsize() == 2


def test_tenant_bound_raises_scope_tenant_and_spares_others():
    q = _q(max_tenant=2)
    q.put((0, 0, _req("a0", tenant="ns/a")), bounded=True)
    q.put((0, 1, _req("a1", tenant="ns/a")), bounded=True)
    with pytest.raises(QueueFullError) as ei:
        q.put((0, 2, _req("a2", tenant="ns/a")), bounded=True)
    assert ei.value.scope == "tenant"
    assert ei.value.tenant == "ns/a"
    # The other tenant still has room.
    q.put((0, 3, _req("b0", tenant="ns/b")), bounded=True)
    assert q.qsize() == 3


def test_unbounded_put_ignores_caps():
    """Engine-internal re-queues (fault survivors, preempt replay) must
    never be shed: the engine already accepted these requests."""
    q = _q(max_total=1)
    q.put((0, 0, _req("r0")), bounded=True)
    q.put((0, 1, _req("r1")))          # internal re-queue
    assert q.qsize() == 2


def test_plain_mode_bounds_apply_too():
    q = _q(fair=False, max_tenant=1)
    q.put((0, 0, _req("a0", tenant="ns/a")), bounded=True)
    with pytest.raises(QueueFullError):
        q.put((0, 1, _req("a1", tenant="ns/a")), bounded=True)


# ------------------------------------------------------------- plain mode


def test_plain_mode_is_the_old_heap():
    q = _q(fair=False)
    for i in range(40):
        q.put((0, i, _req(f"a{i}", tenant="ns/flood")))
    q.put((0, 40, _req("v0", tenant="ns/victim")))
    order = _drain(q)
    # FIFO within the tier: the victim waits behind the whole flood —
    # exactly the starvation the fair mode exists to fix (and the bench's
    # ARKS_FAIR=0 control arm).
    assert order.index("v0") == 40


# ----------------------------------------------------------------- blocking


def test_get_timeout_raises_stdlib_empty():
    q = _q()
    t0 = time.monotonic()
    with pytest.raises(stdq.Empty):
        q.get(timeout=0.05)
    assert time.monotonic() - t0 < 5.0
    assert q.head_prio() is None


def test_head_prio_reports_best_tier():
    q = _q()
    q.put((2, 0, _req("r0", priority=2)))
    assert q.head_prio() == 2
    q.put((0, 1, _req("r1")))
    assert q.head_prio() == 0
    q.put((-5, 2, _req("r2")))
    assert q.head_prio() == -5


# -------------------------------------------------------------------- aging


def test_aging_promotes_in_arrival_order():
    q = _q()
    old_a = _req("old-a", tenant="ns/a", priority=2)
    old_b = _req("old-b", tenant="ns/a", priority=2)
    old_a.arrival_time -= 10
    old_b.arrival_time -= 10
    q.put((2, 0, old_a))
    q.put((2, 1, old_b))
    q.put((0, 2, _req("fresh", tenant="ns/a")))
    q.age_tick(time.monotonic(), aging_s=4.0)
    # elapsed 10s / 4s = 2 rungs: both tier-2 entries reach tier 0, in
    # arrival order, behind nothing (same tier now) — seq keeps them
    # ordered among themselves and against the fresh tier-0 entry.
    order = _drain(q)
    assert order == ["old-a", "old-b", "fresh"]


def test_aging_plain_mode_matches():
    q = _q(fair=False)
    old = _req("old", priority=2)
    old.arrival_time -= 10
    q.put((2, 0, old))
    q.put((1, 1, _req("mid", priority=1)))
    q.age_tick(time.monotonic(), aging_s=4.0)
    assert _drain(q) == ["old", "mid"]


def test_aging_never_touches_urgent():
    q = _q()
    r = _req("replay")
    r.arrival_time -= 100
    q.put((-2 ** 20, 0, r))
    q.age_tick(time.monotonic(), aging_s=1.0)
    assert q.get_nowait()[0] == -2 ** 20


# -------------------------------------------------- retry-after / saturation


def test_retry_after_defaults_without_drain_evidence():
    q = _q()
    assert q.retry_after() == 5


def test_retry_after_derives_from_drain_rate():
    q = _q()
    for i in range(64):
        q.put((0, i, _req(f"r{i}")))
    for _ in range(32):
        q.get_nowait()
    ra = q.retry_after()
    assert 1 <= ra <= 120


def test_saturation_report():
    q = _q(max_total=10)
    for i in range(5):
        q.put((0, i, _req(f"r{i}", tenant=f"ns/t{i % 2}")))
    s = q.saturation()
    assert s["queue_depth"] == 5
    assert s["queue_max"] == 10
    assert s["tenants_waiting"] == 2
    assert s["saturation"] == 0.5
    assert s["fair"] is True


def test_request_cost_floor():
    assert request_cost(_req("r", prompt=0, max_tokens=0)) == 1
    assert request_cost(_req("r", prompt=3, max_tokens=2)) == 5


# ------------------------------------------------------------------ tenancy


def test_parse_weights():
    assert tenancy.parse_weights("ns/a:2,ns/b:0.5") == {
        "ns/a": 2.0, "ns/b": 0.5}
    with pytest.raises(ValueError):
        tenancy.parse_weights("ns/a")
    with pytest.raises(ValueError):
        tenancy.parse_weights("ns/a:zero")
    with pytest.raises(ValueError):
        tenancy.parse_weights("ns/a:0")


def test_tenant_labels_bounded():
    labels = tenancy.TenantLabels(cap=3)
    assert labels.label("ns/a") == "ns/a"
    assert labels.label("ns/b") == "ns/b"
    assert labels.label(None) == tenancy.DEFAULT_TENANT
    # Cap reached: every later tenant shares the overflow bucket, known
    # tenants keep their own label.
    assert labels.label("ns/late") == tenancy.OTHER_LABEL
    assert labels.label("ns/a") == "ns/a"
    with pytest.raises(ValueError):
        tenancy.TenantLabels(cap=0)
