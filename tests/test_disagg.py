"""Disaggregated (prefill/decode-separated) serving tests.

Covers the three layers the reference outsources to SGLang + its router
(SURVEY.md §2.4 "Prefill/Decode disaggregation"):
1. engine: detached prefill -> KV wire format -> prefilled admission is
   bit-identical to a unified run (greedy),
2. control plane: DisaggregatedApplication phase machine, 3 gangsets,
   router service, endpoint discovery (fake driver),
3. full stack: real prefill/decode/router subprocesses behind the gateway.
"""

import json
import time
import urllib.request

import numpy as np
import pytest

from arks_tpu.control import resources as res
from arks_tpu.control.manager import build_manager
from arks_tpu.control.workloads import FakeGangDriver, LocalProcessDriver
from arks_tpu.engine import kv_transfer
from arks_tpu.engine.engine import EngineConfig, InferenceEngine
from arks_tpu.engine.tokenizer import ByteTokenizer
from arks_tpu.engine.types import PrefilledState, Request, SamplingParams
from arks_tpu.gateway.server import Gateway
from arks_tpu.models import get_config


def wait_for(predicate, timeout=30.0, interval=0.1):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        v = predicate()
        if v:
            return v
        time.sleep(interval)
    raise AssertionError("condition not met within timeout")


# ---------------------------------------------------------------------------
# 1. Engine-level KV handoff
# ---------------------------------------------------------------------------


def _drain(req: Request) -> list[int]:
    toks: list[int] = []
    while True:
        out = req.outputs.get(timeout=60)
        toks.extend(out.token_ids)
        if out.finished:
            return toks


def test_kv_transfer_roundtrip():
    rng = np.random.default_rng(0)
    k = rng.standard_normal((2, 1, 8, 2, 4)).astype(np.float32)
    v = rng.standard_normal((2, 1, 8, 2, 4)).astype(np.float32)
    meta = {"first_token": 7, "num_prompt": 5, "seed": 3}
    buf = kv_transfer.pack(meta, [k, v])
    meta2, (k2, v2) = kv_transfer.unpack(buf)
    assert meta2 == meta
    np.testing.assert_array_equal(k, k2)
    np.testing.assert_array_equal(v, v2)


def test_kv_transfer_bfloat16():
    import jax.numpy as jnp

    k = np.asarray(jnp.arange(16, dtype=jnp.bfloat16).reshape(1, 1, 4, 1, 4))
    _, (k2,) = kv_transfer.unpack(kv_transfer.pack({}, [k]))
    assert str(k2.dtype) == "bfloat16"
    np.testing.assert_array_equal(np.asarray(k, np.float32),
                                  np.asarray(k2, np.float32))


def test_disaggregated_matches_unified():
    """Greedy prefill-on-A + decode-on-B == unified decode, token for token."""
    cfg = get_config("tiny")
    ecfg = EngineConfig(model="tiny", num_slots=2, max_cache_len=64,
                        prefill_buckets=(16, 32), steps_per_dispatch=2)
    tok = ByteTokenizer()
    # Shared params: same seed => same init on both engines.
    unified = InferenceEngine(cfg, ecfg, tok)
    prompt = tok.encode("hello disaggregation")
    params = SamplingParams(max_tokens=8, temperature=0.0, ignore_eos=True)

    unified.start()
    try:
        ureq = Request(request_id="u1", prompt_ids=prompt, params=params)
        unified.add_request(ureq)
        expected = _drain(ureq)
    finally:
        unified.stop()

    prefill_engine = InferenceEngine(cfg, ecfg, tok)   # no decode loop
    decode_engine = InferenceEngine(cfg, ecfg, tok)
    pf = prefill_engine.prefill_detached(prompt, params)
    assert pf.num_prompt == len(prompt)

    # Through the wire format, as the servers would send it.
    meta, tensors = kv_transfer.unpack(kv_transfer.pack(
        {"first_token": pf.first_token, "num_prompt": pf.num_prompt,
         "seed": pf.seed}, [np.asarray(pf.k), np.asarray(pf.v)]))

    decode_engine.start()
    try:
        dreq = Request(request_id="d1", prompt_ids=[], params=params,
                       prefilled=PrefilledState(
                           first_token=meta["first_token"],
                           num_prompt=meta["num_prompt"],
                           seed=meta["seed"], k=tensors[0], v=tensors[1]))
        decode_engine.add_request(dreq)
        got = _drain(dreq)
    finally:
        decode_engine.stop()

    assert got == expected
    assert len(got) == 8


def test_disaggregated_guided_decoding():
    """Guided request across the disagg pair: the prefill engine samples
    the first token under the guide, the decode engine rebases the
    RELATIVE DFA row onto its own table (compiled in a different order
    here, to prove rebasing), and the full output matches the grammar."""
    import json as _json
    cfg = get_config("tiny")
    ecfg = EngineConfig(model="tiny", num_slots=2, max_cache_len=96,
                        prefill_buckets=(16, 32), steps_per_dispatch=2)
    tok = ByteTokenizer()
    pat = r'\{"ok": (true|false)\}'
    params = SamplingParams(max_tokens=24, temperature=0.0,
                            guide=("regex", pat))
    prefill_engine = InferenceEngine(cfg, ecfg, tok)
    decode_engine = InferenceEngine(cfg, ecfg, tok)
    # Skew the decode engine's table layout: an unrelated guide compiled
    # FIRST shifts this guide's start_row vs the prefill engine's.
    decode_engine.guides.compile("regex", "[a-z]+")
    pf = prefill_engine.prefill_detached(tok.encode("zz"), params)
    g = prefill_engine.guides.lookup("regex", pat)
    assert 0 <= pf.guide_row < g.n_states

    decode_engine.start()
    try:
        dreq = Request(request_id="dg1", prompt_ids=[], params=params,
                       prefilled=PrefilledState(
                           first_token=pf.first_token,
                           num_prompt=pf.num_prompt, seed=pf.seed,
                           k=pf.k, v=pf.v, guide_row=pf.guide_row))
        decode_engine.add_request(dreq)
        got = _drain(dreq)
    finally:
        decode_engine.stop()
    text = tok.decode(got)  # _register_slot emits the first token too
    assert _json.loads(text)["ok"] in (True, False)


def test_admit_prefilled_refreshes_guide_tables():
    """Regression (advisor high-severity): _admit_prefilled set guide_row
    WITHOUT refreshing the device guide tables, unlike every other
    admission path — a guide published after the step's top-of-loop
    refresh (routine now that compiles finish on worker threads at
    arbitrary times, and the ordering tests/test_spec_decode.py followed
    by test_disagg.py::test_disaggregated_guided_decoding hit in one
    process) decoded against stale device rows: all -1 -> everything
    masked -> instant eos."""
    import json as _json
    cfg = get_config("tiny")
    ecfg = EngineConfig(model="tiny", num_slots=2, max_cache_len=96,
                        prefill_buckets=(16, 32), steps_per_dispatch=2)
    tok = ByteTokenizer()
    pat = r'\{"ok": (true|false)\}'
    params = SamplingParams(max_tokens=24, temperature=0.0,
                            guide=("regex", pat))
    prefill_engine = InferenceEngine(cfg, ecfg, tok)
    pf = prefill_engine.prefill_detached(tok.encode("zz"), params)

    decode_engine = InferenceEngine(cfg, ecfg, tok)
    decode_engine._ensure_guides_uploaded()  # the top-of-loop refresh
    # The guide publishes AFTER that refresh (what a worker-pool compile
    # finishing mid-step looks like): device tables are now stale.
    decode_engine.guides.compile(*params.guide)
    assert decode_engine._guide_ver != decode_engine.guides.version
    dreq = Request(request_id="rg1", prompt_ids=[], params=params,
                   prefilled=PrefilledState(
                       first_token=pf.first_token, num_prompt=pf.num_prompt,
                       seed=pf.seed, k=pf.k, v=pf.v,
                       guide_row=pf.guide_row))
    decode_engine.metrics.num_requests_waiting.inc(1)  # _preadmit decs
    assert decode_engine._preadmit(dreq) is None  # prefilled admits inline
    # THE regression check: the admission must have refreshed the device
    # tables before the slot's first decode dispatch.
    assert decode_engine._guide_ver == decode_engine.guides.version
    decode_engine.start()
    try:
        got = _drain(dreq)
    finally:
        decode_engine.stop()
    text = tok.decode(got)
    assert _json.loads(text)["ok"] in (True, False)


def test_detached_prefill_rejects_oversize_prompt():
    """The disaggregated prefill engine raises the typed rejection (the
    servers map it to HTTP 400 context_length_exceeded end-to-end, including
    across the decode server's KV pull)."""
    from arks_tpu.engine.engine import ContextLengthExceededError
    cfg = get_config("tiny")
    ecfg = EngineConfig(model="tiny", num_slots=1, max_cache_len=16,
                        prefill_buckets=(8,), steps_per_dispatch=4)
    eng = InferenceEngine(cfg, ecfg, ByteTokenizer())
    with pytest.raises(ContextLengthExceededError):
        eng.prefill_detached(list(range(50)), SamplingParams())


def test_prefilled_too_long_is_aborted():
    cfg = get_config("tiny")
    ecfg = EngineConfig(model="tiny", num_slots=1, max_cache_len=16,
                        prefill_buckets=(8,), steps_per_dispatch=4)
    eng = InferenceEngine(cfg, ecfg, ByteTokenizer())
    eng.start()
    try:
        req = Request(request_id="big", prompt_ids=[], params=SamplingParams(),
                      prefilled=PrefilledState(
                          first_token=1, num_prompt=100, seed=0,
                          k=np.zeros((cfg.num_layers, 1, 8, cfg.num_kv_heads,
                                      cfg.head_dim), np.float32),
                          v=np.zeros((cfg.num_layers, 1, 8, cfg.num_kv_heads,
                                      cfg.head_dim), np.float32)))
        eng.add_request(req)
        out = req.outputs.get(timeout=30)
        assert out.finished and out.finish_reason == "abort"
    finally:
        eng.stop()


# ---------------------------------------------------------------------------
# 2. Control plane (fake driver)
# ---------------------------------------------------------------------------


@pytest.fixture()
def fake_stack(tmp_path):
    driver = FakeGangDriver()
    mgr = build_manager(models_root=str(tmp_path / "models"), driver=driver)
    mgr.start()
    yield mgr, driver
    mgr.stop()


def test_disaggregated_phase_machine(fake_stack, tmp_path):
    mgr, driver = fake_stack
    store = mgr.store
    store.create(res.Model(name="m", spec={"model": "test/m"}))
    store.create(res.DisaggregatedApplication(name="pd", spec={
        "model": {"name": "m"}, "servedModelName": "pd-served",
        "modelConfig": "tiny",
        "router": {"replicas": 1},
        "prefill": {"replicas": 1, "tensorParallel": 1},
        "decode": {"replicas": 2},
    }))

    wait_for(lambda: store.get(res.DisaggregatedApplication, "pd")
             .status.get("phase") == res.PHASE_RUNNING)
    app = store.get(res.DisaggregatedApplication, "pd")
    assert app.status["decode"]["readyReplicas"] == 2
    assert app.ready()

    # Three gangsets with the right commands.
    pre = store.get(res.GangSet, "pd-prefill")
    dec = store.get(res.GangSet, "pd-decode")
    rtr = store.get(res.GangSet, "pd-router")
    assert "--disaggregation-mode" in pre.spec["leader"]["command"]
    assert "prefill" in pre.spec["leader"]["command"]
    assert "decode" in dec.spec["leader"]["command"]
    assert "arks_tpu.router" in " ".join(rtr.spec["leader"]["command"])

    # Router service + endpoint discovery.
    svc = store.get(res.Service, "pd-router-svc")
    assert svc.spec["selector"][res.LABEL_ROLE] == "router"

    store.create(res.Endpoint(name="pd-served", spec={}))
    routes = wait_for(lambda: store.get(res.Endpoint, "pd-served")
                      .status.get("routes") or None)
    assert routes[0]["backend"]["service"] == "pd-router-svc"

    # Component failure flips readiness off.
    driver.fail_group(("default", "pd-decode"), 0)
    wait_for(lambda: not store.get(res.DisaggregatedApplication, "pd").ready())

    # Deleting the app cascades its workloads.
    store.delete(res.DisaggregatedApplication, "pd")
    wait_for(lambda: store.try_get(res.GangSet, "pd-router") is None)


def test_disaggregated_tier_size_derives_from_accelerator(fake_stack):
    """Disagg tiers size their gangs from the accelerator shape exactly
    like the Application path (live and gitops renderings must agree):
    multi-host shapes set size, multi-slice ones add --num-slices, and
    the unified unit PodGroup counts every pod across slices."""
    mgr, driver = fake_stack
    store = mgr.store
    store.create(res.Model(name="m-acc", spec={"model": "test/m"}))
    store.create(res.DisaggregatedApplication(name="pda", spec={
        "mode": "unified",
        "model": {"name": "m-acc"}, "servedModelName": "pda-served",
        "modelConfig": "tiny",
        "podGroupPolicy": {"kubeScheduling": {}},
        "router": {"replicas": 1},
        "prefill": {"replicas": 1, "accelerator": "tpu-v5e-16"},
        "decode": {"replicas": 1, "accelerator": "tpu-v5p-16x2"},
    }))
    pre = wait_for(lambda: store.try_get(res.GangSet, "pda-prefill"))
    dec = wait_for(lambda: store.try_get(res.GangSet, "pda-decode"))
    assert pre.spec["size"] == 4                       # v5e-16: 4 hosts
    assert dec.spec["size"] == 4                       # 2 slices x 2 hosts
    assert "--num-slices 2" in " ".join(dec.spec["leader"]["command"])
    assert "--num-slices" not in " ".join(pre.spec["leader"]["command"])
    # Unit PodGroup spans router + all tier pods across slices: 1 + 4 + 4.
    assert pre.spec["podGroupUnit"]["minMember"] == 9
    store.delete(res.DisaggregatedApplication, "pda")
    wait_for(lambda: store.try_get(res.GangSet, "pda-router") is None)


def test_disaggregated_rejects_non_jax_runtime(fake_stack):
    mgr, _ = fake_stack
    store = mgr.store
    store.create(res.DisaggregatedApplication(name="bad", spec={
        "runtime": "vllm", "model": {"name": "nope"}}))
    wait_for(lambda: store.get(res.DisaggregatedApplication, "bad")
             .status.get("phase") == res.PHASE_FAILED)


# ---------------------------------------------------------------------------
# 3. Full-stack e2e: real subprocesses + gateway
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def pd_stack(tmp_path_factory):
    root = tmp_path_factory.mktemp("pd-e2e")
    driver = LocalProcessDriver(log_dir=str(root / "logs"))
    mgr = build_manager(models_root=str(root / "models"), driver=driver,
                        local_platform="cpu")
    mgr.start()
    gw = Gateway(mgr.store, host="127.0.0.1", port=0, quota_sync_s=0.5)
    gw.start(background=True)
    yield mgr, gw
    gw.stop()
    mgr.stop()
    for gs in mgr.store.list(res.GangSet):
        driver.teardown(gs)


def test_disaggregated_end_to_end(pd_stack):
    mgr, gw = pd_stack
    store = mgr.store

    store.create(res.Model(name="pd-model", spec={"model": "test/pd"}))
    store.create(res.DisaggregatedApplication(name="pd-app", spec={
        "model": {"name": "pd-model"}, "servedModelName": "pd-served",
        "modelConfig": "tiny",
        "router": {"replicas": 1},
        "prefill": {"replicas": 1,
                    "runtimeCommonArgs": ["--num-slots", "2",
                                          "--max-model-len", "64"]},
        "decode": {"replicas": 1,
                   "runtimeCommonArgs": ["--num-slots", "2",
                                         "--max-model-len", "64"]},
    }))
    store.create(res.Endpoint(name="pd-served", spec={}))
    store.create(res.Token(name="pd-user", spec={
        "token": "sk-pd",
        "qos": [{"endpoint": {"name": "pd-served"},
                 "rateLimits": [{"type": "rpm", "value": 50}]}]}))

    # Three subprocesses must boot (jax import + compile each).
    wait_for(lambda: store.get(res.DisaggregatedApplication, "pd-app")
             .status.get("phase") == res.PHASE_RUNNING, timeout=300,
             interval=0.5)
    wait_for(lambda: (store.get(res.Endpoint, "pd-served").status.get("routes")
                      or None), timeout=30, interval=0.25)

    req = urllib.request.Request(
        f"http://127.0.0.1:{gw.port}/v1/chat/completions",
        data=json.dumps({
            "model": "pd-served",
            "messages": [{"role": "user", "content": "hello pd"}],
            "max_tokens": 6, "temperature": 0, "ignore_eos": True,
        }).encode(),
        headers={"Content-Type": "application/json",
                 "Authorization": "Bearer sk-pd"})
    with urllib.request.urlopen(req, timeout=180) as r:
        data = json.load(r)
    assert data["object"] == "chat.completion"
    assert data["usage"]["completion_tokens"] == 6
    assert data["choices"][0]["finish_reason"] == "length"

    # Streaming through router + decode + gateway.
    req = urllib.request.Request(
        f"http://127.0.0.1:{gw.port}/v1/chat/completions",
        data=json.dumps({
            "model": "pd-served",
            "messages": [{"role": "user", "content": "stream pd"}],
            "max_tokens": 4, "temperature": 0, "ignore_eos": True,
            "stream": True, "stream_options": {"include_usage": True},
        }).encode(),
        headers={"Content-Type": "application/json",
                 "Authorization": "Bearer sk-pd"})
    frames = []
    with urllib.request.urlopen(req, timeout=180) as r:
        for raw in r:
            line = raw.decode().strip()
            if line.startswith("data: "):
                frames.append(line[6:])
    assert frames[-1] == "[DONE]"
    usage_frames = [f for f in frames
                    if f != "[DONE]" and json.loads(f).get("usage")]
    assert usage_frames, "usage frame missing from disaggregated stream"
    assert json.loads(usage_frames[-1])["usage"]["completion_tokens"] == 4


# ---------------------------------------------------------------------------
# Router policy: cache_aware prefix affinity
# ---------------------------------------------------------------------------


def test_cache_aware_policy_pins_shared_prefixes():
    import json as _json

    from arks_tpu.router import Discovery, Router, _prefix_key, _rendezvous

    r = Router(Discovery(None), "m", policy="cache_aware")
    prefill = ["p1:1", "p2:1", "p3:1"]
    decode = ["d1:1", "d2:1"]
    sys_prompt = "You are a helpful assistant. " * 40  # > key window
    def body(user):
        return _json.dumps({"model": "m", "messages": [
            {"role": "system", "content": sys_prompt},
            {"role": "user", "content": user}]}).encode()

    picks = {r._pick(body(f"question {i}"), prefill, decode)
             for i in range(10)}
    # Same (long) system prompt -> same prefill AND decode every time,
    # regardless of the divergent user turn.
    assert len(picks) == 1

    # A different system prompt is free to land elsewhere; the key differs.
    k1 = _prefix_key(body("x"))
    k2 = _prefix_key(_json.dumps({"model": "m", "messages": [
        {"role": "system", "content": "Terse answers only. " * 40}]}).encode())
    assert k1 != k2

    # Rendezvous: removing an unrelated backend keeps the assignment.
    chosen = _rendezvous(k1, prefill)
    rest = [b for b in prefill if b != chosen]
    survivors = [b for b in prefill if b in ([chosen] + rest[:1])]
    assert _rendezvous(k1, survivors) == chosen


def test_round_robin_policy_spreads():
    import json as _json

    from arks_tpu.router import Discovery, Router

    r = Router(Discovery(None), "m", policy="round_robin")
    prefill = ["p1:1", "p2:1"]
    decode = ["d1:1", "d2:1"]
    b = _json.dumps({"model": "m", "prompt": "same"}).encode()
    picks = {r._pick(b, prefill, decode) for _ in range(4)}
    assert len(picks) == 2  # alternates


def test_prefix_key_robust_to_garbage():
    from arks_tpu.router import _prefix_key
    assert _prefix_key(b"not json") is None
    assert _prefix_key(b"{}") is None
    assert _prefix_key(b'{"messages": "nope"}') is None
    assert _prefix_key(b'{"prompt": "hi"}') is not None


def test_prefix_key_content_parts():
    """Content-part messages key on their serialized text parts; unknown
    content shapes stop the scan instead of skipping to a later turn."""
    import json as _json

    from arks_tpu.router import _prefix_key

    def body(messages):
        return _json.dumps({"model": "m", "messages": messages}).encode()

    tail = [{"role": "user", "content": "same tail question"}]
    parts_a = [{"role": "system", "content": [
        {"type": "text", "text": "persona A instructions"}]}] + tail
    parts_b = [{"role": "system", "content": [
        {"type": "text", "text": "persona B instructions"}]}] + tail
    ka, kb = _prefix_key(body(parts_a)), _prefix_key(body(parts_b))
    assert ka is not None and kb is not None and ka != kb
    # Same as the equivalent plain-string message.
    plain = [{"role": "system", "content": "persona A instructions"}] + tail
    assert _prefix_key(body(plain)) == ka

    # Unknown content shape in the FIRST message: never key on later turns.
    weird = [{"role": "system", "content": {"mystery": 1}}] + tail
    assert _prefix_key(body(weird)) is None


def test_prefix_key_content_parts_edge_shapes():
    """Null text values don't raise; image-only first messages don't key
    on later turns."""
    import json as _json

    from arks_tpu.router import _prefix_key

    def body(messages):
        return _json.dumps({"model": "m", "messages": messages}).encode()

    tail = [{"role": "user", "content": "tail"}]
    assert _prefix_key(body(
        [{"role": "u", "content": [{"type": "text", "text": None}]}] + tail
    )) is None
    assert _prefix_key(body(
        [{"role": "u", "content": [{"type": "image_url",
                                    "image_url": {"url": "x"}}]}] + tail
    )) is None


# ---------------------------------------------------------------------------
# Kubernetes label-selector service discovery (reference --service-discovery)
# ---------------------------------------------------------------------------


def _pod(name, app, role, ip, port, ready=True, phase="Running"):
    return {
        "apiVersion": "v1", "kind": "Pod",
        "metadata": {"name": name, "namespace": "default",
                     "labels": {"arks.ai/application": app,
                                "arks.ai/component": role}},
        "spec": {"containers": [{"name": "engine",
                                 "ports": [{"containerPort": port}]}]},
        "status": {"phase": phase, "podIP": ip,
                   "conditions": [{"type": "Ready",
                                   "status": "True" if ready else "False"}]},
    }


def test_kube_discovery_selects_ready_leader_pods(monkeypatch):
    from arks_tpu.control.k8s_client import FakeKubeApi
    from arks_tpu.router import KubeDiscovery

    monkeypatch.delenv("ARKS_PREFILL_ADDRS", raising=False)
    monkeypatch.delenv("ARKS_DECODE_ADDRS", raising=False)
    api = FakeKubeApi()
    api.create("v1", "pods", "default", _pod("p0", "d1", "prefill", "10.0.0.1", 8080))
    api.create("v1", "pods", "default",
               _pod("p1", "d1", "prefill", "10.0.0.2", 8080, ready=False))
    api.create("v1", "pods", "default", _pod("d0", "d1", "decode", "10.0.0.3", 9090))
    api.create("v1", "pods", "default", _pod("x0", "OTHER", "decode", "10.0.0.4", 8080))
    api.create("v1", "pods", "default",
               _pod("d2", "d1", "decode", "10.0.0.5", 9090, phase="Pending"))

    disc = KubeDiscovery(api, "default", "d1", interval_s=0.0)
    prefill, decode = disc.backends()
    # Only READY Running pods of THIS app; addr = podIP:containerPort
    # (workers 503 their readiness, so only gang leaders appear).
    assert prefill == ["10.0.0.1:8080"]
    assert decode == ["10.0.0.3:9090"]

    # Pod churn is picked up on the next refresh.
    api.create("v1", "pods", "default", _pod("d3", "d1", "decode", "10.0.0.6", 9090))
    _, decode = disc.backends()
    assert decode == ["10.0.0.3:9090", "10.0.0.6:9090"]


def test_kube_discovery_env_fallback_until_pods_appear(monkeypatch):
    from arks_tpu.control.k8s_client import FakeKubeApi
    from arks_tpu.router import KubeDiscovery

    monkeypatch.setenv("ARKS_PREFILL_ADDRS", "svc-p:8080")
    monkeypatch.setenv("ARKS_DECODE_ADDRS", "svc-d:8080")
    api = FakeKubeApi()
    disc = KubeDiscovery(api, "default", "d1", interval_s=0.0)
    assert disc.backends() == (["svc-p:8080"], ["svc-d:8080"])
    api.create("v1", "pods", "default", _pod("p0", "d1", "prefill", "10.0.0.1", 8080))
    prefill, decode = disc.backends()
    assert prefill == ["10.0.0.1:8080"]   # discovered pods replace env
    assert decode == ["svc-d:8080"]       # tier without pods keeps fallback


def test_kube_discovery_prefers_http_named_port(monkeypatch):
    """A metrics port declared first (or a sidecar container ordered first)
    must not hijack routing: the port named ``http`` wins; with several
    unnamed ports and no ``http``, fall back to backend_port."""
    from arks_tpu.control.k8s_client import FakeKubeApi
    from arks_tpu.router import KubeDiscovery

    monkeypatch.delenv("ARKS_PREFILL_ADDRS", raising=False)
    monkeypatch.delenv("ARKS_DECODE_ADDRS", raising=False)
    api = FakeKubeApi()
    pod = _pod("p0", "d1", "prefill", "10.0.0.1", 9999)
    pod["spec"]["containers"] = [
        {"name": "sidecar", "ports": [{"containerPort": 9400,
                                       "name": "metrics"}]},
        {"name": "engine", "ports": [{"containerPort": 9999},
                                     {"containerPort": 8081, "name": "http"}]},
    ]
    api.create("v1", "pods", "default", pod)
    amb = _pod("d0", "d1", "decode", "10.0.0.2", 9999)
    amb["spec"]["containers"] = [
        {"name": "engine", "ports": [{"containerPort": 9400},
                                     {"containerPort": 9999}]}]
    api.create("v1", "pods", "default", amb)
    met = _pod("d1p", "d1", "decode", "10.0.0.3", 9400)
    met["spec"]["containers"] = [
        {"name": "engine", "ports": [{"containerPort": 9400,
                                      "name": "metrics"}]}]
    api.create("v1", "pods", "default", met)

    disc = KubeDiscovery(api, "default", "d1", backend_port=8080,
                         interval_s=0.0)
    prefill, decode = disc.backends()
    assert prefill == ["10.0.0.1:8081"]   # named http beats declared order
    # Ambiguous unnamed pair AND a lone named-metrics port both fall back.
    assert decode == ["10.0.0.2:8080", "10.0.0.3:8080"]


def test_router_with_kube_discovery_end_to_end():
    """A real Router using KubeDiscovery against a (fake) apiserver routes
    to real in-process prefill/decode servers discovered as pods — the
    live-mode deployment shape, minus the kubelet."""
    import urllib.error

    from arks_tpu.control.k8s_client import FakeApiServer, FakeKubeApi, KubeApi
    from arks_tpu.router import KubeDiscovery, Router
    from arks_tpu.server.disagg import DecodeServer, PrefillServer

    cfg = get_config("tiny")

    def eng(**kw):
        return InferenceEngine(
            cfg, EngineConfig(model="tiny", num_slots=2, max_cache_len=64,
                              prefill_buckets=(16, 32),
                              steps_per_dispatch=2), ByteTokenizer(), **kw)

    pre_e, dec_e = eng(), eng()
    dec_e.start()
    pre = PrefillServer(pre_e, served_model_name="t", host="127.0.0.1", port=0)
    dec = DecodeServer(dec_e, served_model_name="t", host="127.0.0.1", port=0)
    pre.start(background=True)
    dec.start(background=True)

    fake = FakeKubeApi()
    srv = FakeApiServer(fake)
    srv.start()
    url = srv.url
    fake.create("v1", "pods", "default",
                _pod("pre-0", "dapp", "prefill", "127.0.0.1", pre.port))
    fake.create("v1", "pods", "default",
                _pod("dec-0", "dapp", "decode", "127.0.0.1", dec.port))

    disc = KubeDiscovery(KubeApi(url), "default", "dapp", interval_s=0.0)
    router = Router(disc, "t", host="127.0.0.1", port=0)
    router.start(background=True)
    try:
        body = json.dumps({"model": "t", "prompt": "hi there", "max_tokens": 6,
                           "temperature": 0, "ignore_eos": True}).encode()
        req = urllib.request.Request(
            f"http://127.0.0.1:{router.port}/v1/completions", data=body,
            headers={"Content-Type": "application/json"})
        out = json.load(urllib.request.urlopen(req, timeout=60))
        assert out["usage"]["completion_tokens"] == 6
        assert out["choices"][0]["text"]
    finally:
        router.stop()
        pre.stop()
        dec.stop()
        dec_e.stop()
        srv.stop()


def test_disagg_logprobs_match_unified():
    """A disaggregated logprob request returns the SAME logprob stream as
    the unified path (first token from the transferred PrefilledState, the
    rest from the decode side's own dispatches) — round-2 VERDICT hole."""
    import urllib.request as _url

    from arks_tpu.server import OpenAIServer
    from arks_tpu.server.disagg import DecodeServer, PrefillServer

    cfg = get_config("tiny")

    def eng():
        return InferenceEngine(
            cfg, EngineConfig(model="tiny", num_slots=2, max_cache_len=64,
                              prefill_buckets=(16, 32),
                              steps_per_dispatch=2), ByteTokenizer())

    uni_e, pre_e, dec_e = eng(), eng(), eng()
    uni_e.start()
    dec_e.start()
    uni = OpenAIServer(uni_e, served_model_name="t", host="127.0.0.1", port=0)
    pre = PrefillServer(pre_e, served_model_name="t", host="127.0.0.1", port=0)
    dec = DecodeServer(dec_e, served_model_name="t", host="127.0.0.1", port=0)
    for s in (uni, pre, dec):
        s.start(background=True)

    body = {"model": "t", "prompt": "logprob parity", "max_tokens": 5,
            "temperature": 0, "ignore_eos": True, "logprobs": 2, "seed": 7}

    def post(port, path, headers=None):
        req = _url.Request(
            f"http://127.0.0.1:{port}{path}",
            data=json.dumps(body).encode(),
            headers={"Content-Type": "application/json", **(headers or {})})
        return json.load(_url.urlopen(req, timeout=60))

    try:
        ref = post(uni.port, "/v1/completions")["choices"][0]
        got = post(dec.port, "/v1/disagg/completions",
                   {"X-Arks-Prefill-Addr": f"127.0.0.1:{pre.port}"})["choices"][0]
    finally:
        for s in (uni, pre, dec):
            s.stop()
        uni_e.stop()
        dec_e.stop()

    assert got["text"] == ref["text"]
    glp, rlp = got["logprobs"], ref["logprobs"]
    assert glp["tokens"] == rlp["tokens"]
    assert glp["text_offset"] == rlp["text_offset"]
    assert len(glp["token_logprobs"]) == 5
    for a, b in zip(glp["token_logprobs"], rlp["token_logprobs"]):
        assert abs(a - b) < 1e-3
    for da, db in zip(glp["top_logprobs"], rlp["top_logprobs"]):
        assert set(da) == set(db)


def test_disagg_prefill_on_gang_dispatcher():
    """Detached prefill on a multi-host gang: the dispatch is mirrored to
    followers (prefill_detached ops) instead of raising — round-2 VERDICT
    hole.  (The real 2-process gang path rides test_e2e_local's gang
    tests; here a recording dispatcher proves the emit contract.)"""
    class RecordingDispatcher:
        def __init__(self):
            self.ops = []

        def broadcast(self, op, payload):
            self.ops.append(op)

    cfg = get_config("tiny")
    eng = InferenceEngine(
        cfg, EngineConfig(model="tiny", num_slots=2, max_cache_len=64,
                          prefill_buckets=(16, 32), steps_per_dispatch=2),
        ByteTokenizer())
    eng.dispatcher = RecordingDispatcher()
    pf = eng.prefill_detached([3, 4, 5], SamplingParams(temperature=0.0))
    assert pf.num_prompt == 3 and pf.first_lp is None
    pf2 = eng.prefill_detached([3, 4, 5],
                               SamplingParams(temperature=0.0, logprobs=1))
    assert pf2.first_lp is not None
    assert pf2.first_token == pf.first_token
    assert eng.dispatcher.ops == ["prefill_detached", "prefill_detached_lp"]


def test_disaggregated_gang_prefill_e2e(pd_stack):
    """VERDICT acceptance (round-2 item 4): a size-2 multi-process PREFILL
    gang serves the PD path — detached prefills are mirrored to the gang
    follower (prefill_detached ops) and the transferred KV decodes
    correctly, including logprobs on the continuation."""
    mgr, gw = pd_stack
    store = mgr.store

    store.create(res.Model(name="pdg-model", spec={"model": "test/pdg"}))
    store.create(res.DisaggregatedApplication(name="pdg-app", spec={
        "model": {"name": "pdg-model"}, "servedModelName": "pdg-served",
        "modelConfig": "tiny",
        "router": {"replicas": 1},
        "prefill": {"replicas": 1, "size": 2, "tensorParallel": 2,
                    "runtimeCommonArgs": ["--num-slots", "2",
                                          "--max-model-len", "64"]},
        "decode": {"replicas": 1,
                   "runtimeCommonArgs": ["--num-slots", "2",
                                         "--max-model-len", "64"]},
    }))
    store.create(res.Endpoint(name="pdg-served", spec={}))
    store.create(res.Token(name="pdg-user", spec={
        "token": "sk-pdg",
        "qos": [{"endpoint": {"name": "pdg-served"},
                 "rateLimits": [{"type": "rpm", "value": 50}]}]}))

    # Four subprocesses boot (router + 2-process prefill gang + decode).
    wait_for(lambda: store.get(res.DisaggregatedApplication, "pdg-app")
             .status.get("phase") == res.PHASE_RUNNING, timeout=300,
             interval=0.5)
    wait_for(lambda: (store.get(res.Endpoint, "pdg-served")
                      .status.get("routes") or None), timeout=30,
             interval=0.25)

    req = urllib.request.Request(
        f"http://127.0.0.1:{gw.port}/v1/completions",
        data=json.dumps({
            "model": "pdg-served", "prompt": "gang prefill",
            "max_tokens": 5, "temperature": 0, "ignore_eos": True,
            "logprobs": 1,
        }).encode(),
        headers={"Content-Type": "application/json",
                 "Authorization": "Bearer sk-pdg"})
    with urllib.request.urlopen(req, timeout=180) as r:
        data = json.load(r)
    assert data["usage"]["completion_tokens"] == 5
    lp = data["choices"][0]["logprobs"]
    assert len(lp["token_logprobs"]) == 5  # incl. the transferred first token
    assert all(v <= 0 for v in lp["token_logprobs"])

    # Second request exercises the steady-state gang (follower mirrored a
    # full prefill cycle and survived).
    with urllib.request.urlopen(urllib.request.Request(
            f"http://127.0.0.1:{gw.port}/v1/completions",
            data=json.dumps({
                "model": "pdg-served", "prompt": "again",
                "max_tokens": 3, "temperature": 0, "ignore_eos": True,
            }).encode(),
            headers={"Content-Type": "application/json",
                     "Authorization": "Bearer sk-pdg"}), timeout=120) as r:
        assert json.load(r)["usage"]["completion_tokens"] == 3
