"""Fleet-wide prefix restore: a replica fetches a peer's warm prefix
blocks over ``GET /v1/cache/blocks/{digest}`` instead of re-prefilling.

Engine A warms a shared prefix and (after churn spills it to its host
tier) serves the raw pool-native blocks from its OpenAI server; engine B
admits the same prompt with ``X-Arks-Peer-Hint`` semantics (the
``Request.peer_hint`` field the server maps the header to), parks in the
fetch path, stages A's blocks into its own tier 1, and restores — the
generated stream is byte-identical to both A's and a no-fetch control,
with strictly fewer chunk-prefilled tokens.  A peer dying mid-fetch
degrades to re-prefill of the unfetched span; the request is unharmed.
"""

import http.server
import threading
import urllib.error
import urllib.request

import numpy as np
import pytest

from arks_tpu.engine import (EngineConfig, InferenceEngine, Request,
                             SamplingParams)
from arks_tpu.engine import kv_transfer
from arks_tpu.engine.paged import chain_digests
from arks_tpu.engine.tokenizer import ByteTokenizer
from arks_tpu.models import get_config
from arks_tpu.server import OpenAIServer


def _mk(monkeypatch, peer_fetch="0"):
    monkeypatch.setenv("ARKS_PIPELINE_DEPTH", "0")
    monkeypatch.setenv("ARKS_MIXED_STEP", "auto")
    monkeypatch.setenv("ARKS_PREFIX_HOST_MB", "64")
    monkeypatch.delenv("ARKS_PREFIX_DISK_MB", raising=False)
    monkeypatch.delenv("ARKS_PEER_ADDRS", raising=False)
    monkeypatch.setenv("ARKS_PEER_FETCH", peer_fetch)
    monkeypatch.setenv("ARKS_PEER_FETCH_TIMEOUT_S", "5")
    cfg = get_config("tiny")
    eng = InferenceEngine(
        cfg, EngineConfig(model="tiny", num_slots=2, max_cache_len=64,
                          prefill_buckets=(8, 16, 32), steps_per_dispatch=4,
                          prefill_chunk=16, kv_layout="paged",
                          prefix_cache_mb=0),
        ByteTokenizer())
    return cfg, eng


def _drive(eng, n_steps=2000):
    for _ in range(n_steps):
        try:
            eng.step(block_s=0.01)
        except Exception as e:  # noqa: BLE001 — routed like _run_loop
            eng._recover_from_fault(e)
        if (eng.num_running == 0 and eng._queue.empty()
                and not eng._prefilling and not eng._awaiting_fetch
                and not eng._awaiting_restore and eng.state == "serving"):
            break


def _collect(req, timeout=120):
    ids, fin = [], None
    while True:
        out = req.outputs.get(timeout=timeout)
        ids.extend(out.token_ids)
        if out.finished:
            fin = out
            break
    return ids, fin


def _run_one(eng, rid, ids, peer_hint=None, max_tokens=4):
    req = Request(rid, ids, SamplingParams(
        max_tokens=max_tokens, temperature=0.0, ignore_eos=True),
        peer_hint=peer_hint)
    eng.add_request(req)
    _drive(eng)
    return _collect(req)


def _warm_peer(monkeypatch):
    """Engine A with the warm prefix resident in its HOST tier (churn
    evicts the device pages, spilling them into tier 1 — which is what
    block_for_export serves)."""
    cfg, a = _mk(monkeypatch)
    warm = [int(x) % cfg.vocab_size for x in range(3, 36)]  # 2 pages + tail
    base = _run_one(a, "w1", warm)
    for i in range(5):
        _run_one(a, f"ch{i}", [(9 + i) % cfg.vocab_size] * 33, max_tokens=3)
    digests = chain_digests(warm, 16, 2)
    assert all(a._host.has(d) for d in digests), \
        "churn did not spill the warm prefix into the host tier"
    return a, warm, digests, base


def test_block_export_endpoint_round_trips(monkeypatch):
    a, warm, digests, _ = _warm_peer(monkeypatch)
    srv = OpenAIServer(a, served_model_name="t", host="127.0.0.1", port=0)
    srv.start(background=True)
    try:
        url = f"http://127.0.0.1:{srv.port}/v1/cache/blocks/"
        with urllib.request.urlopen(url + digests[0].hex(), timeout=30) as r:
            assert r.status == 200
            buf = r.read()
        blk = kv_transfer.unpack_block(buf, digests[0], a.kv_epoch)
        ref = a.block_for_export(digests[0])
        assert set(blk) == set(ref)
        for f in ref:
            assert blk[f].tobytes() == np.asarray(ref[f]).tobytes()

        # Absent digest and junk both map to 404, never a traceback.
        for tail in ("ff" * 20, "not-hex"):
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(url + tail, timeout=30)
            assert ei.value.code == 404
    finally:
        srv.stop()
        a.stop()


def test_peer_fetch_restores_instead_of_reprefilling(monkeypatch):
    a, warm, digests, base = _warm_peer(monkeypatch)
    srv = OpenAIServer(a, served_model_name="t", host="127.0.0.1", port=0)
    srv.start(background=True)

    _, ctrl = _mk(monkeypatch)          # no-fetch control: re-prefills
    got_ctrl = _run_one(ctrl, "c1", warm)
    ctrl_chunk = ctrl.metrics.mixed_chunk_tokens_total.total()

    _, b = _mk(monkeypatch, peer_fetch="1")
    try:
        got = _run_one(b, "w2", warm,
                       peer_hint=f"127.0.0.1:{srv.port}")
        assert got[0] == base[0] == got_ctrl[0], \
            "peer-fetched stream diverged from the re-prefilled one"
        assert got[1].finish_reason == base[1].finish_reason == "length"
        m = b.metrics
        assert m.prefix_peer_fetch_blocks_total.get(source="peer") == 2
        assert m.prefix_cache_hit_tokens_total.get(tier="peer") == 32
        assert m.prefix_restore_blocks_total.total() >= 2
        # Strictly fewer chunk-prefilled tokens than the no-fetch control.
        assert m.mixed_chunk_tokens_total.total() < ctrl_chunk
        assert sum(m.engine_faults_total._values.values()) == 0
        assert b.state == "serving"
    finally:
        b.stop()
        ctrl.stop()
        srv.stop()
        a.stop()


class _DyingPeer(http.server.ThreadingHTTPServer):
    """Serves ONE valid block, then drops every later connection mid-
    request — the peer-death-during-fetch shape."""

    daemon_threads = True

    def __init__(self, payloads):
        self.payloads = dict(payloads)  # path -> bytes
        self.served = 0
        super().__init__(("127.0.0.1", 0), _DyingPeerHandler)


class _DyingPeerHandler(http.server.BaseHTTPRequestHandler):
    def do_GET(self):  # noqa: N802 — http.server API
        srv = self.server
        buf = srv.payloads.get(self.path)
        if srv.served >= 1 or buf is None:
            # Mid-fetch death: slam the connection, no HTTP response.
            self.connection.close()
            return
        srv.served += 1
        self.send_response(200)
        self.send_header("Content-Type", "application/octet-stream")
        self.send_header("Content-Length", str(len(buf)))
        self.end_headers()
        self.wfile.write(buf)

    def log_message(self, *a):  # quiet
        pass


def test_mid_fetch_peer_death_falls_back_to_reprefill(monkeypatch):
    """The peer serves block 1 then dies: the staged partial run
    restores, the rest chunk-prefills, and the request finishes
    byte-identical to a never-fetched run — latency cost only."""
    a, warm, digests, base = _warm_peer(monkeypatch)
    payloads = {
        f"/v1/cache/blocks/{d.hex()}":
            kv_transfer.pack_block(d, a.kv_epoch, a.block_for_export(d))
        for d in digests
    }
    a.stop()
    peer = _DyingPeer(payloads)
    threading.Thread(target=peer.serve_forever, daemon=True).start()

    _, b = _mk(monkeypatch, peer_fetch="1")
    try:
        got = _run_one(b, "w2", warm,
                       peer_hint=f"127.0.0.1:{peer.server_address[1]}")
        assert got[0] == base[0], "stream diverged after mid-fetch peer death"
        assert got[1].finish_reason == "length"
        m = b.metrics
        assert m.prefix_peer_fetch_blocks_total.get(source="peer") == 1
        assert m.prefix_cache_hit_tokens_total.get(tier="peer") == 16
        assert sum(m.engine_faults_total._values.values()) == 0
        assert sum(m.requests_quarantined_total._values.values()) == 0
        assert b.state == "serving"
    finally:
        b.stop()
        peer.shutdown()


def test_dead_peer_from_the_start_costs_nothing_but_latency(monkeypatch):
    """A hint pointing at a closed port: the fetch stages nothing and the
    admission degrades to plain chunked prefill."""
    import socket
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    dead_port = s.getsockname()[1]
    s.close()

    _, ctrl = _mk(monkeypatch)
    cfg = get_config("tiny")
    warm = [int(x) % cfg.vocab_size for x in range(3, 36)]
    got_ctrl = _run_one(ctrl, "c1", warm)
    ctrl.stop()

    _, b = _mk(monkeypatch, peer_fetch="1")
    try:
        got = _run_one(b, "w2", warm, peer_hint=f"127.0.0.1:{dead_port}")
        assert got[0] == got_ctrl[0]
        assert got[1].finish_reason == "length"
        assert b.metrics.prefix_peer_fetch_blocks_total.get(
            source="peer") == 0
        assert b.state == "serving"
    finally:
        b.stop()
