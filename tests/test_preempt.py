"""SLO-tiered preemptive KV swap: latency-tier requests seize running
slots by spilling low-tier decode state to host RAM.

Acceptance surface for the preemption machinery (engine.py, the
ARKS_PREEMPT paths):

- a preempted-and-resumed stream is BYTE-IDENTICAL to its unpreempted
  run (greedy + seeded + guided, pipeline depths 0 and 2) in both swap
  mode (host tier on) and replay mode (the fallback when there is no
  host tier, or on spec engines — the tested fallback-matrix rows);
- chaos: a fault injected during the preempt spill, the harvest, or the
  victim resume quarantines ONLY the culprit attempt — every stream
  still completes byte-identically via token replay;
- abort-while-swapped-out releases the victim's host bytes and never
  drives the parked/waiting gauges negative;
- ARKS_QUEUE_AGING_S decays a starved batch request's effective
  priority until it admits under sustained latency-tier load.

Engines are driven synchronously through the step/_recover_from_fault
contract (the _run_loop shape) so faults land deterministically.
"""

import pytest

from arks_tpu.engine import EngineConfig, InferenceEngine, Request, SamplingParams
from arks_tpu.engine.tokenizer import ByteTokenizer
from arks_tpu.models import get_config

CHUNK = 16


def _mk_engine(monkeypatch, depth=0, host_mb=64, preempt=True, inject=None,
               **kw):
    monkeypatch.setenv("ARKS_MIXED_STEP", "auto")
    monkeypatch.setenv("ARKS_PIPELINE_DEPTH", str(depth))
    monkeypatch.setenv("ARKS_PREFIX_HOST_MB", str(host_mb))
    monkeypatch.setenv("ARKS_PREEMPT", "1" if preempt else "0")
    monkeypatch.setenv("ARKS_SLO_TIERS", "latency:ttft_ms=300,batch:")
    if inject is None:
        monkeypatch.delenv("ARKS_FAULT_INJECT", raising=False)
    else:
        monkeypatch.setenv("ARKS_FAULT_INJECT", inject)
    cfg = get_config("tiny")
    defaults = dict(model="tiny", num_slots=1, max_cache_len=64,
                    prefill_buckets=(8, 16, 32), steps_per_dispatch=1,
                    prefill_chunk=CHUNK, kv_layout="paged",
                    prefix_cache_mb=0)
    defaults.update(kw)
    eng = InferenceEngine(cfg, EngineConfig(**defaults), ByteTokenizer())
    if depth:
        assert eng._pipe_warm_wait(300) == "ready"
    return cfg, eng


def _drive(eng, n_steps=4000):
    """The engine thread's own step/recover contract, synchronously."""
    for _ in range(n_steps):
        try:
            eng.step(block_s=0.01)
        except Exception as e:  # noqa: BLE001 — routed exactly like _run_loop
            eng._recover_from_fault(e)
        if eng.idle and eng.state == "serving":
            break


def _collect(req, timeout=120):
    ids, fin = [], None
    while True:
        out = req.outputs.get(timeout=timeout)
        ids.extend(out.token_ids)
        if out.finished:
            fin = out
            break
    return ids, fin


def _victims(cfg, guided=False):
    """Low-tier (priority 1) long decodes — the preemption victims.
    Greedy and seeded-sampled; optionally one guided stream."""
    sp_greedy = SamplingParams(max_tokens=20, temperature=0.0,
                               ignore_eos=True, priority=1)
    sp_seeded = SamplingParams(max_tokens=20, temperature=0.9, top_p=0.9,
                               top_k=40, seed=21, ignore_eos=True, priority=1)
    reqs = [Request("bt-greedy", [5, 6, 7], sp_greedy),
            Request("bt-seeded", [9] * 5, sp_seeded)]
    if guided:
        reqs.append(Request("bt-guided", [8, 3, 4], SamplingParams(
            max_tokens=24, temperature=0.9, seed=33, ignore_eos=True,
            priority=1, guide=("regex", "[a-f]+"))))
    return reqs


def _latency_req(i=0, max_tokens=4):
    return Request(f"lt-{i}", [2, 2, 2, 3 + i], SamplingParams(
        max_tokens=max_tokens, temperature=0.0, ignore_eos=True, priority=0))


def _run_scenario(monkeypatch, depth, host_mb, preempt, inject=None,
                  guided=False, **kw):
    """One victim at a time on a 1-slot engine: admit a batch request,
    decode a few tokens, land a latency-tier arrival (the preemption
    trigger when enabled), drain, repeat for each victim."""
    cfg, eng = _mk_engine(monkeypatch, depth=depth, host_mb=host_mb,
                          preempt=preempt, inject=inject, **kw)
    outs = {}
    for i, victim in enumerate(_victims(cfg, guided=guided)):
        eng.add_request(victim)
        for _ in range(14):
            try:
                eng.step(block_s=0.01)
            except Exception as e:  # noqa: BLE001
                eng._recover_from_fault(e)
        lat = _latency_req(i)
        eng.add_request(lat)
        _drive(eng)
        outs[victim.request_id] = _collect(victim)
        outs[lat.request_id] = _collect(lat)
    return outs, eng


@pytest.mark.parametrize("depth", [0, 2])
def test_preempt_swap_streams_byte_identical(monkeypatch, depth):
    """Swap mode (host tier on): greedy, seeded, and guided victims are
    preempted mid-decode, swapped to host RAM, resumed — and every
    stream (victims AND the latency arrivals that seized their slots) is
    byte-identical to the preemption-off run, at depths 0 and 2."""
    base, _ = _run_scenario(monkeypatch, depth, 64, preempt=False,
                            guided=True)
    got, eng = _run_scenario(monkeypatch, depth, 64, preempt=True,
                             guided=True)
    assert eng.resolved_config["preempt"] == "swap"
    pre = eng.metrics.requests_preempted_total.total()
    assert pre >= 3, f"expected every victim preempted, got {pre}"
    assert got == base, "streams diverged across preempt on/off"
    # Host-byte hygiene: nothing left swapped out after drain.
    assert len(eng._swap) == 0
    assert eng._host.reserved == 0
    assert eng.metrics.requests_parked.get(reason="preempt") == 0


def test_preempt_swap_int4_pool_byte_identical(monkeypatch):
    """int4 KV pool through the preempt-swap path: the swap snapshot
    gathers raw PACKED pool bytes (nibble pairs + scale stripes), so a
    preempted-and-resumed victim's stream is byte-identical to the
    preemption-off run — the int4 counterpart of the swap-mode gate."""
    kw = dict(kv_cache_dtype="int4")
    base, _ = _run_scenario(monkeypatch, 0, 64, preempt=False, **kw)
    got, eng = _run_scenario(monkeypatch, 0, 64, preempt=True, **kw)
    assert eng._cache.kv_bits == 4
    assert eng.resolved_config["preempt"] == "swap"
    assert eng.metrics.requests_preempted_total.total() >= 2
    assert got == base, "int4 streams diverged across preempt on/off"
    assert len(eng._swap) == 0 and eng._host.reserved == 0


def test_preempt_replay_fallback_byte_identical(monkeypatch):
    """Replay mode (no host tier): preemption discards device state and
    re-enters the victim through token replay — streams still
    byte-identical.  This is the fallback-matrix row for slot-layout /
    pp>1 / dp engines (any engine without the host tier)."""
    base, _ = _run_scenario(monkeypatch, 0, 0, preempt=False)
    got, eng = _run_scenario(monkeypatch, 0, 0, preempt=True)
    assert eng.resolved_config["preempt"] == "replay"
    assert eng.metrics.requests_preempted_total.total() >= 2
    assert got == base, "replay-mode streams diverged across preempt on/off"


def test_spec_engine_preempts_via_replay(monkeypatch):
    """Fallback-matrix row for speculative engines: the draft cache has
    no swap snapshot, so a spec engine preempts in REPLAY mode even with
    the host tier on — and streams stay byte-identical."""
    kw = dict(draft_model="tiny", draft_len=3)
    base, _ = _run_scenario(monkeypatch, 0, 64, preempt=False, **kw)
    got, eng = _run_scenario(monkeypatch, 0, 64, preempt=True, **kw)
    assert eng.resolved_config["preempt"] == "replay"
    assert eng.metrics.requests_preempted_total.total() >= 2
    assert got == base, "spec streams diverged across preempt on/off"


@pytest.mark.chaos
@pytest.mark.parametrize("depth", [0, 2])
@pytest.mark.parametrize("nth,where", [(1, "spill-issue"), (2, "harvest"),
                                       (3, "resume")],
                         ids=["spill-issue", "harvest", "resume"])
def test_preempt_fault_recovers_byte_identical(monkeypatch, depth, nth,
                                               where):
    """Chaos rows for the 'preempt' phase: a fault injected during the
    preempt spill issue (1st fire), the D2H harvest (2nd), or the victim
    resume (3rd) must quarantine only that attempt — the victim re-enters
    through token replay and EVERY stream completes byte-identically,
    with zero quarantined requests, at depths 0 and 2."""
    base, _ = _run_scenario(monkeypatch, depth, 64, preempt=False,
                            guided=True)
    got, eng = _run_scenario(monkeypatch, depth, 64, preempt=True,
                             inject=f"preempt:{nth}:runtime", guided=True)
    assert got == base, \
        f"streams diverged after a {where} fault (depth {depth})"
    assert eng.metrics.engine_faults_total.total() == 1
    assert eng.metrics.requests_quarantined_total.total() == 0
    assert eng.state == "serving"
    assert len(eng._swap) == 0
    assert eng._host.reserved == 0


def test_abort_while_swapped_releases_host_bytes(monkeypatch):
    """Aborting a victim while its decode state sits in host RAM must
    free the SwapStore bytes (and the shared tier budget reservation)
    and resolve the request as an abort — with the parked/waiting gauges
    landing at exactly zero, never negative."""
    cfg, eng = _mk_engine(monkeypatch, preempt=True)
    victim = Request("victim", [5, 6, 7], SamplingParams(
        max_tokens=40, temperature=0.0, ignore_eos=True, priority=1))
    eng.add_request(victim)
    for _ in range(14):
        eng.step(block_s=0.01)
    lat = _latency_req(0, max_tokens=30)
    eng.add_request(lat)
    # Step until the victim's swap landed in the SwapStore (it stays
    # there while the latency request holds the only slot).
    for _ in range(400):
        eng.step(block_s=0.01)
        if "victim" in eng._swapped and "victim" in eng._swap:
            break
    else:
        pytest.fail("victim never reached the swapped-out state")
    assert eng._swap.bytes_used > 0
    assert eng._host.reserved > 0
    assert eng.metrics.requests_parked.get(reason="preempt") >= 1
    eng.abort("victim")
    _drive(eng)
    ids, fin = _collect(victim)
    assert fin.finish_reason == "abort"
    _collect(lat)
    assert len(eng._swap) == 0 and eng._swap.bytes_used == 0
    assert eng._host.reserved == 0
    assert eng.metrics.requests_parked.get(reason="preempt") == 0
    assert eng.metrics.num_requests_waiting.get() >= 0
    for key, v in eng.metrics.requests_parked._values.items():
        assert v >= 0, (key, v)


def test_swap_shares_the_host_tier_byte_budget(monkeypatch):
    """The SwapStore carves its bytes out of the host prefix tier's
    budget (reserved), so a swap can evict prefix blocks but the
    combined footprint never exceeds ARKS_PREFIX_HOST_MB."""
    cfg, eng = _mk_engine(monkeypatch, preempt=True, host_mb=64)
    victim = Request("victim", [5, 6, 7], SamplingParams(
        max_tokens=40, temperature=0.0, ignore_eos=True, priority=1))
    eng.add_request(victim)
    for _ in range(14):
        eng.step(block_s=0.01)
    eng.add_request(_latency_req(0, max_tokens=30))
    for _ in range(400):
        eng.step(block_s=0.01)
        if "victim" in eng._swap:
            break
    else:
        pytest.fail("victim never swapped out")
    t = eng._host
    assert t.reserved == eng._swap.bytes_used
    assert t._bytes + t.reserved <= t.capacity
    _drive(eng)
    assert t.reserved == 0


def test_queue_aging_admits_starved_batch_request(monkeypatch):
    """ARKS_QUEUE_AGING_S regression: under sustained latency-tier load
    that would otherwise starve it forever, a batch-tier request's
    effective priority decays to 0 and it admits (and finishes)."""
    monkeypatch.setenv("ARKS_QUEUE_AGING_S", "0.05")
    cfg, eng = _mk_engine(monkeypatch, preempt=False)
    starved = Request("starved", [7, 7, 7], SamplingParams(
        max_tokens=4, temperature=0.0, ignore_eos=True, priority=1))
    eng.add_request(starved)
    fin = None
    i = 0
    for _ in range(1500):
        # Sustained latency-tier pressure: keep the queue non-empty with
        # priority-0 arrivals so, without aging, "starved" never reaches
        # the head.
        if eng._queue.qsize() < 2:
            eng.add_request(_latency_req(i, max_tokens=2))
            i += 1
        eng.step(block_s=0.01)
        while not starved.outputs.empty():
            out = starved.outputs.get_nowait()
            if out.finished:
                fin = out
        if fin is not None:
            break
    assert fin is not None, "batch request starved despite ARKS_QUEUE_AGING_S"
    assert fin.finish_reason == "length"


def test_aging_disabled_keeps_strict_priority_order(monkeypatch):
    """With aging off (the default), a continuous latency-tier stream
    keeps the batch request queued — the behavior aging exists to fix
    (and the control run that makes the regression above meaningful)."""
    monkeypatch.delenv("ARKS_QUEUE_AGING_S", raising=False)
    cfg, eng = _mk_engine(monkeypatch, preempt=False)
    starved = Request("starved", [7, 7, 7], SamplingParams(
        max_tokens=4, temperature=0.0, ignore_eos=True, priority=1))
    eng.add_request(starved)
    i = 0
    for _ in range(300):
        if eng._queue.qsize() < 2:
            eng.add_request(_latency_req(i, max_tokens=2))
            i += 1
        eng.step(block_s=0.01)
        assert starved.outputs.empty(), \
            "batch request admitted without aging — control run is broken"
