"""Mixed prefill+decode scheduling (ARKS_MIXED_STEP): token-exact parity
vs the legacy chunk+decode path, single-dispatch-per-step, aborts mid-
prefill, and guides publishing while mixed batches flow."""

import json
import time

import pytest

from arks_tpu.engine import EngineConfig, InferenceEngine, Request, SamplingParams
from arks_tpu.engine.tokenizer import ByteTokenizer
from arks_tpu.models import get_config

# Every op on the dispatch channel that runs the MODEL (admission state
# writes like set_slot/clear_penalties are not dispatches of the model).
MODEL_DISPATCH_OPS = {
    "mixed", "spec_mixed", "decode", "chunk", "chunk_paged", "admit_batch",
    "admit_batch_lp", "draft_prefill", "prefill_detached",
    "prefill_detached_lp", "sample_one", "sample_one_lp",
}


class RecordingDispatcher:
    def __init__(self):
        self.ops = []

    def broadcast(self, op, payload):
        self.ops.append((op, payload))


def _mk_engine(monkeypatch, mixed: str, **kw):
    monkeypatch.setenv("ARKS_MIXED_STEP", mixed)
    cfg = get_config("tiny")
    defaults = dict(model="tiny", num_slots=2, max_cache_len=64,
                    prefill_buckets=(8, 16, 32), steps_per_dispatch=4,
                    prefill_chunk=16, kv_layout="paged")
    defaults.update(kw)
    ecfg = EngineConfig(**defaults)
    return cfg, InferenceEngine(cfg, ecfg, ByteTokenizer())


def _collect(req, timeout=120):
    ids, lps, fin = [], [], None
    while True:
        out = req.outputs.get(timeout=timeout)
        ids.extend(out.token_ids)
        if out.logprobs:
            lps.extend(out.logprobs)
        if out.finished:
            fin = out
            break
    return ids, lps, fin


def _drive(engine, n_steps=500):
    for _ in range(n_steps):
        engine.step(block_s=0.01)
        if (engine.num_running == 0 and engine._queue.empty()
                and not engine._prefilling):
            break


def test_mixed_matches_legacy_token_exact(monkeypatch):
    """Mixed vs legacy must produce IDENTICAL token streams on CPU: greedy
    and fixed-seed sampled, short (one-shot-sized) and chunked prompts,
    logprobs on and off, with slot churn (more requests than slots)."""
    cfg = get_config("tiny")
    prompts = [[5, 6, 7], [3] * 20, list(range(3, 51)), [9] * 10, [4, 8]]

    def run(mixed):
        _, eng = _mk_engine(monkeypatch, mixed)
        assert eng._mixed == (mixed == "auto")
        reqs = []
        for i, p in enumerate(prompts):
            if i % 2 == 0:
                sp = SamplingParams(max_tokens=6, temperature=0.0,
                                    ignore_eos=True,
                                    logprobs=2 if i == 0 else None)
            else:
                sp = SamplingParams(max_tokens=6, temperature=0.8,
                                    top_p=0.9, top_k=40, seed=42 + i,
                                    ignore_eos=True)
            reqs.append(Request(f"r{i}", [int(x) % cfg.vocab_size for x in p],
                                sp))
        for r in reqs:
            eng.add_request(r)
        _drive(eng)
        outs = []
        for r in reqs:
            ids, lps, fin = _collect(r)
            outs.append((ids, lps, fin.finish_reason,
                         fin.num_prompt_tokens))
        return outs

    mixed, legacy = run("auto"), run("0")
    # Token streams are EXACT; logprob floats come from different compiled
    # programs (mixed forward vs prefill/decode loop) — same math,
    # blockwise, so only fp reassociation separates them.
    for (m_ids, m_lps, m_fin, m_np), (l_ids, l_lps, l_fin, l_np) in zip(
            mixed, legacy):
        assert m_ids == l_ids
        assert (m_fin, m_np) == (l_fin, l_np)
        assert len(m_lps) == len(l_lps)
        for (m_clp, m_top), (l_clp, l_top) in zip(m_lps, l_lps):
            assert abs(m_clp - l_clp) < 5e-3
            assert [t for t, _ in m_top] == [t for t, _ in l_top]
            for (_, mv), (_, lv) in zip(m_top, l_top):
                assert abs(mv - lv) < 5e-3


def test_mixed_single_model_dispatch_per_step(monkeypatch):
    """With decodes active AND a prefill chunk pending, one scheduler step
    issues EXACTLY ONE model dispatch — the acceptance criterion the whole
    tentpole exists for (legacy pays one chunk dispatch + one decode
    dispatch in that state)."""
    cfg, eng = _mk_engine(monkeypatch, "auto")
    eng.dispatcher = RecordingDispatcher()

    # A short request reaches decode...
    short = Request("s", [5, 6], SamplingParams(max_tokens=40,
                                                temperature=0.0,
                                                ignore_eos=True))
    eng.add_request(short)
    for _ in range(50):
        eng.step(block_s=0.01)
        if eng._slots:
            break
    assert eng._slots
    # ...then a long prompt starts chunked prefill (48 tokens, chunk 16).
    long_req = Request("l", [int(x) % cfg.vocab_size for x in range(3, 51)],
                       SamplingParams(max_tokens=2, temperature=0.0,
                                      ignore_eos=True))
    eng.add_request(long_req)
    for _ in range(50):
        eng.step(block_s=0.01)
        if eng._prefilling:
            break
    assert eng._slots and eng._prefilling

    pos_before = next(iter(eng._prefilling.values())).pos
    tokens_before = len(eng._slots[next(iter(eng._slots))].generated)
    eng.dispatcher.ops.clear()
    eng.step(block_s=0.01)
    model_ops = [op for op, _ in eng.dispatcher.ops
                 if op in MODEL_DISPATCH_OPS]
    assert model_ops == ["mixed"], model_ops
    # ...and that single dispatch advanced BOTH the decode and the prefill.
    assert len(eng._slots[next(iter(eng._slots))].generated) \
        == tokens_before + 1
    st = next(iter(eng._prefilling.values()), None)
    assert st is None or st.pos > pos_before
    _drive(eng)
    _collect(short)
    _collect(long_req)


def test_mixed_round_robin_spreads_budget_across_prefills(monkeypatch):
    """Two concurrent long prompts must BOTH make progress in one mixed
    step (the legacy scheduler only ever advanced the FIFO head)."""
    cfg, eng = _mk_engine(monkeypatch, "auto", num_slots=4)
    longs = [Request(f"l{i}", [(3 + i + x) % cfg.vocab_size
                               for x in range(48)],
                     SamplingParams(max_tokens=2, temperature=0.0,
                                    ignore_eos=True))
             for i in range(2)]
    for r in longs:
        eng.add_request(r)
    for _ in range(10):
        eng.step(block_s=0.01)
        if len(eng._prefilling) == 2:
            break
    assert len(eng._prefilling) == 2
    before = {s: st.pos for s, st in eng._prefilling.items()}
    eng.step(block_s=0.01)
    after = {s: st.pos for s, st in eng._prefilling.items()}
    advanced = [s for s in before if s not in after or after[s] > before[s]]
    assert len(advanced) == 2, (before, after)
    _drive(eng)
    for r in longs:
        _collect(r)


def test_mixed_abort_prefilling_between_steps(monkeypatch):
    """Aborting a sequence mid-chunked-prefill frees its slot and pages at
    the next mixed boundary and fails the request with reason=abort."""
    cfg, eng = _mk_engine(monkeypatch, "auto", prefix_cache_mb=0)
    free_pages = eng._alloc.free_pages
    long_req = Request("al", [int(x) % cfg.vocab_size for x in range(3, 51)],
                       SamplingParams(max_tokens=2, temperature=0.0,
                                      ignore_eos=True))
    eng.add_request(long_req)
    st = None
    for _ in range(30):
        eng.step(block_s=0.01)
        st = next(iter(eng._prefilling.values()), None)
        if st is not None and st.pos > 0:
            break
    assert st is not None and 0 < st.pos < len(st.ids)  # mid-prefill
    eng.abort("al")
    eng.step(block_s=0.01)
    assert not eng._prefilling
    ids, _, fin = _collect(long_req)
    assert fin.finish_reason == "abort" and not ids
    assert eng._alloc.free_pages == free_pages  # pages reclaimed
    assert len(eng._free) == eng.ecfg.num_slots

    # The engine still serves afterwards.
    ok = Request("ok", [5, 6, 7], SamplingParams(max_tokens=3,
                                                 temperature=0.0,
                                                 ignore_eos=True))
    eng.add_request(ok)
    _drive(eng)
    ids, _, fin = _collect(ok)
    assert len(ids) == 3 and fin.finish_reason == "length"


def test_mixed_guided_request_publishes_mid_batches(monkeypatch):
    """A guided request whose guide compiles WHILE mixed dispatches are in
    flight: the request parks (never blocking the scheduler), decode keeps
    flowing through mixed steps, and once the guide publishes the request
    admits through the chunked path and its output obeys the grammar."""
    cfg, eng = _mk_engine(monkeypatch, "auto", max_cache_len=96)
    eng.start()
    try:
        tok = ByteTokenizer()
        # Keep a decode stream alive for the whole compile window.
        load = Request("load", tok.encode("zz"), SamplingParams(
            max_tokens=200, temperature=0.0, ignore_eos=True))
        eng.add_request(load)
        load.outputs.get(timeout=120)  # decoding

        orig = eng.guides._build

        def slow_build(rx):
            time.sleep(1.5)
            return orig(rx)

        eng.guides._build = slow_build
        pat = r'\{"k": (true|false)\}'
        greq = Request("g", tok.encode("zz"), SamplingParams(
            max_tokens=48, temperature=0.0, guide=("regex", pat)))
        eng.add_request(greq)
        time.sleep(0.1)
        # While the compile is in flight, the mixed scheduler must keep
        # producing decode tokens (the request parks; nothing blocks).
        produced = 0
        deadline = time.monotonic() + 1.0
        while time.monotonic() < deadline:
            try:
                produced += len(load.outputs.get(timeout=0.2).token_ids)
            except Exception:
                pass
        assert produced > 0, "decode stalled behind the guide compile"
        toks = []
        while True:
            out = greq.outputs.get(timeout=120)
            toks.extend(out.token_ids)
            if out.finished:
                break
        assert out.finish_reason == "stop"
        assert json.loads(ByteTokenizer().decode(toks))["k"] in (True, False)
        eng.abort("load")
    finally:
        eng.stop()


def test_mixed_disabled_for_unsupported_engines(monkeypatch):
    """Non-paged engines stay on the legacy scheduler even when
    ARKS_MIXED_STEP=1 asks for mixed (with a warning, not a crash).
    Spec engines are different: they REQUIRE mixed and raise instead
    (tests/test_spec_decode.py::test_spec_decode_config_validation)."""
    monkeypatch.setenv("ARKS_MIXED_STEP", "1")
    cfg = get_config("tiny")
    ecfg = EngineConfig(model="tiny", num_slots=2, max_cache_len=64,
                        prefill_buckets=(8, 16, 32), steps_per_dispatch=4,
                        kv_layout="slot")
    eng = InferenceEngine(cfg, ecfg, ByteTokenizer())
    assert not eng._mixed
    assert eng.resolved_config["mixed_step"] == "false"
    req = Request("x", [5, 6, 7], SamplingParams(max_tokens=3,
                                                 temperature=0.0,
                                                 ignore_eos=True))
    eng.add_request(req)
    _drive(eng)
    ids, _, fin = _collect(req)
    assert len(ids) == 3


def test_mixed_env_validation(monkeypatch):
    monkeypatch.setenv("ARKS_MIXED_STEP", "bogus")
    cfg = get_config("tiny")
    ecfg = EngineConfig(model="tiny", num_slots=2, max_cache_len=64,
                        prefill_buckets=(8,), steps_per_dispatch=2,
                        kv_layout="paged", prefill_chunk=16)
    with pytest.raises(ValueError):
        InferenceEngine(cfg, ecfg, ByteTokenizer())
