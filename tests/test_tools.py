"""OpenAI tool calling: parsing, forced-call guides, HTTP round trips.

Parity target: vLLM/SGLang tools/tool_calls on /v1/chat/completions
(launched via arksapplication_controller.go:941-1014)."""

import json
import urllib.error
import urllib.request

import pytest

from arks_tpu.server.tools import (forced_call_guide, parse_tool_calls,
                                   validate_tools)

WEATHER = {"type": "function",
           "function": {"name": "get_weather",
                        "description": "Look up weather",
                        "parameters": {"type": "object", "properties": {
                            "city": {"type": "string"}}}}}
TIME = {"type": "function", "function": {"name": "get_time"}}


# ---------------------------------------------------------------------------
# Unit
# ---------------------------------------------------------------------------

def test_validate_tools():
    assert validate_tools({}) == (None, "none")
    tools, choice = validate_tools({"tools": [WEATHER]})
    assert choice == "auto" and tools[0]["function"]["name"] == "get_weather"
    for bad in ({"tools": []}, {"tools": [{"type": "x"}]},
                {"tools": [WEATHER], "tool_choice": "sometimes"},
                {"tools": [WEATHER],
                 "tool_choice": {"type": "function",
                                 "function": {"name": "nope"}}}):
        with pytest.raises(ValueError):
            validate_tools(bad)


def test_validate_tools_rejects_unsafe_function_names():
    """Names outside [A-Za-z0-9_.-]+ must 400: a quote (or brace, space,
    backslash...) interpolated into the forced-call regex would compile a
    DFA whose forced output parse_tool_calls cannot parse back."""
    for bad_name in ('has"quote', "sp ace", "br{ace", "back\\slash",
                     "pipe|alt", "nl\nline", "paren(s)"):
        body = {"tools": [{"type": "function",
                           "function": {"name": bad_name}}]}
        with pytest.raises(ValueError, match="name"):
            validate_tools(body)
    # The full legal alphabet passes.
    tools, choice = validate_tools(
        {"tools": [{"type": "function",
                    "function": {"name": "get_weather.v2-beta_1"}}]})
    assert choice == "auto"
    assert tools[0]["function"]["name"] == "get_weather.v2-beta_1"


def test_parse_hermes_calls():
    text = ('thinking first <tool_call>{"name": "get_weather", '
            '"arguments": {"city": "Oslo"}}</tool_call> and '
            '<tool_call>{"name": "get_time", "arguments": {}}</tool_call>')
    content, calls = parse_tool_calls(text)
    assert content == "thinking first  and"
    assert [c["function"]["name"] for c in calls] == ["get_weather",
                                                      "get_time"]
    assert json.loads(calls[0]["function"]["arguments"]) == {"city": "Oslo"}
    assert calls[0]["id"].startswith("call_")
    assert calls[0]["type"] == "function"

    # Calls only -> content is None (OpenAI convention).
    content, calls = parse_tool_calls(
        '<tool_call>{"name": "get_time", "arguments": {}}</tool_call>')
    assert content is None and len(calls) == 1

    # Malformed JSON inside the marker stays content.
    content, calls = parse_tool_calls("<tool_call>not json</tool_call>")
    assert calls == [] and "not json" in content


def test_parse_llama3_call():
    content, calls = parse_tool_calls(
        ' {"name": "get_weather", "parameters": {"city": "Pune"}} ')
    assert content is None
    assert calls[0]["function"]["name"] == "get_weather"
    assert json.loads(calls[0]["function"]["arguments"]) == {"city": "Pune"}
    # Plain prose passes through untouched.
    content, calls = parse_tool_calls("just words")
    assert content == "just words" and calls == []


def test_call_spans_raw_coordinates():
    """call_spans reports RAW offsets (streaming emits leftover content
    from them — stripped-content offsets would drop characters)."""
    from arks_tpu.server.tools import call_spans
    text = ('  <tool_call>{"name": "get_time", "arguments": {}}'
            '</tool_call> result: 42')
    (s, e), = call_spans(text)
    assert text[s:].startswith("<tool_call>")
    assert text[:s] == "  " and text[e:] == " result: 42"
    # Unparseable block -> no span (it stays content).
    assert call_spans("<tool_call>junk</tool_call>") == []
    # llama3 whole-message call spans everything.
    assert call_spans(' {"name": "f", "arguments": {}} ') == [(0, 32)]


def test_forced_call_guide_matches_and_parses():
    from arks_tpu.engine.guides import compile_regex_dfa
    kind, pat = forced_call_guide([WEATHER, TIME], "required")
    assert kind == "regex"
    t, a = compile_regex_dfa(pat)

    def match(s):
        st = 0
        for b in s.encode():
            st = t[st, b]
            if st < 0:
                return False
        return bool(a[st])

    good = ('<tool_call>{"name": "get_weather", "arguments": '
            '{"city": "NYC", "n": 3}}</tool_call>')
    assert match(good)
    _, calls = parse_tool_calls(good)
    assert calls and calls[0]["function"]["name"] == "get_weather"
    assert not match('<tool_call>{"name": "other", "arguments": {}}'
                     '</tool_call>')
    assert not match("free text")
    # Named choice narrows to one function.
    _, pat1 = forced_call_guide([WEATHER, TIME],
                                {"type": "function",
                                 "function": {"name": "get_time"}})
    t1, a1 = compile_regex_dfa(pat1)
    s = '<tool_call>{"name": "get_time", "arguments": {}}</tool_call>'
    st = 0
    for b in s.encode():
        st = t1[st, b]
    assert st >= 0 and a1[st]


# ---------------------------------------------------------------------------
# HTTP round trips (forced calls make the random tiny model emit real
# tool-call wire format — the DFA does the formatting)
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def server():
    from arks_tpu.engine import EngineConfig, InferenceEngine
    from arks_tpu.engine.tokenizer import ByteTokenizer
    from arks_tpu.models import get_config
    from arks_tpu.server import OpenAIServer

    cfg = get_config("tiny")
    # ByteTokenizer spends one token per byte, and the textual tools
    # declaration alone is ~270 bytes — size the window accordingly.
    ecfg = EngineConfig(model="tiny", num_slots=2, max_cache_len=640,
                        prefill_buckets=(64, 128, 256, 512),
                        steps_per_dispatch=4)
    engine = InferenceEngine(cfg, ecfg, ByteTokenizer())
    engine.start()
    srv = OpenAIServer(engine, served_model_name="tiny-serve",
                       host="127.0.0.1", port=0)
    srv.start(background=True)
    yield srv
    srv.stop()
    engine.stop()


def _post(server, path, body):
    req = urllib.request.Request(
        f"http://127.0.0.1:{server.port}{path}",
        data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"})
    return urllib.request.urlopen(req, timeout=120)


def test_tool_call_roundtrip_forced(server):
    body = {
        "model": "tiny-serve", "max_tokens": 96, "temperature": 0,
        "messages": [{"role": "user", "content": "what time is it?"}],
        "tools": [WEATHER, TIME],
        "tool_choice": {"type": "function",
                        "function": {"name": "get_time"}},
        # '}' (byte 125 -> id 127) biased +100: the random test model
        # closes the arguments object at the first legal chance, making
        # the forced call minimal and the test length-independent (the
        # guide mask applies AFTER bias, so the bias only acts where '}'
        # is grammatical).
        "logit_bias": {"127": 100},
    }
    with _post(server, "/v1/chat/completions", body) as r:
        data = json.load(r)
    choice = data["choices"][0]
    assert choice["finish_reason"] == "tool_calls"
    calls = choice["message"]["tool_calls"]
    assert calls[0]["function"]["name"] == "get_time"
    json.loads(calls[0]["function"]["arguments"])  # parseable by contract
    assert choice["message"]["content"] is None


def test_tool_call_required_streaming(server):
    body = {
        "model": "tiny-serve", "max_tokens": 96, "temperature": 0,
        "messages": [{"role": "user", "content": "pick any tool"}],
        "tools": [TIME], "tool_choice": "required",
        "logit_bias": {"127": 100},  # see test_tool_call_roundtrip_forced
        "stream": True, "stream_options": {"include_usage": True},
    }
    frames = []
    with _post(server, "/v1/chat/completions", body) as r:
        for raw in r:
            line = raw.decode().strip()
            if line.startswith("data: "):
                frames.append(line[len("data: "):])
    assert frames[-1] == "[DONE]"
    chunks = [json.loads(f) for f in frames[:-1]]
    tc_deltas = [c["choices"][0]["delta"]["tool_calls"]
                 for c in chunks
                 if c["choices"] and "tool_calls" in c["choices"][0]["delta"]]
    assert tc_deltas and tc_deltas[0][0]["function"]["name"] == "get_time"
    finishes = [c["choices"][0]["finish_reason"]
                for c in chunks if c["choices"]]
    assert "tool_calls" in finishes
    assert any(c.get("usage") for c in chunks)


def test_tools_auto_plain_answer_passes_through(server):
    """tool_choice auto with a model that answers in prose: content flows,
    finish_reason stays normal, no tool_calls key."""
    body = {
        "model": "tiny-serve", "max_tokens": 8, "temperature": 0,
        "messages": [{"role": "user", "content": "hello"}],
        "tools": [WEATHER],  # auto by default
        "ignore_eos": True,
    }
    with _post(server, "/v1/chat/completions", body) as r:
        data = json.load(r)
    choice = data["choices"][0]
    assert "tool_calls" not in choice["message"]
    assert choice["finish_reason"] in ("length", "stop")


def test_tool_choice_none_renders_no_tools(server):
    """tool_choice none must not inject the tools declaration into the
    prompt: usage.prompt_tokens matches the same request without tools."""
    base = {
        "model": "tiny-serve", "max_tokens": 2, "temperature": 0,
        "messages": [{"role": "user", "content": "hi"}],
    }
    with _post(server, "/v1/chat/completions", base) as r:
        plain = json.load(r)["usage"]["prompt_tokens"]
    with _post(server, "/v1/chat/completions",
               {**base, "tools": [WEATHER], "tool_choice": "none"}) as r:
        none_toks = json.load(r)["usage"]["prompt_tokens"]
    with _post(server, "/v1/chat/completions",
               {**base, "tools": [WEATHER]}) as r:
        auto_toks = json.load(r)["usage"]["prompt_tokens"]
    assert none_toks == plain
    assert auto_toks > plain


def test_bad_tools_400(server):
    try:
        _post(server, "/v1/chat/completions", {
            "model": "tiny-serve", "max_tokens": 2,
            "messages": [{"role": "user", "content": "x"}],
            "tools": [{"type": "function", "function": {}}]})
        raise AssertionError("expected HTTP 400")
    except urllib.error.HTTPError as e:
        assert e.code == 400
