"""Windowed-residency decode (ARKS_RESIDENCY_WINDOW_PAGES): contexts
strictly larger than the device page pool must decode BYTE-IDENTICAL to
a big-pool control engine.

The windowed engine's pool holds only ``num_slots * window`` pages; a
slot whose decode-grown context outgrows the window engages the
span-streaming path (engine/residency.py) — cold pages live in host RAM
and rotate through on-device staging halves while the resident spans
attend via the carry-chained ragged kernel.  The control engine runs the
same workload with the full logical pool resident.  Token ids, finish
reasons AND logprob floats must match exactly: the residency forward is
built from the same blocks as the mixed program (same batch shapes, same
embed/qkv/update/tail/sampler functions) with only the attend swapped
for the bitwise-proven span chain.
"""

import numpy as np
import pytest

from arks_tpu.engine import EngineConfig, InferenceEngine, Request, SamplingParams
from arks_tpu.engine.tokenizer import ByteTokenizer
from arks_tpu.models import get_config

WINDOW = 6  # pages; pool = num_slots * WINDOW


def _mk_engine(monkeypatch, *, window, depth=0, impl="pallas", **kw):
    monkeypatch.setenv("ARKS_MIXED_STEP", "1")
    monkeypatch.setenv("ARKS_ATTN_IMPL", impl)
    monkeypatch.setenv("ARKS_PIPELINE_DEPTH", str(depth))
    if window:
        monkeypatch.setenv("ARKS_RESIDENCY_WINDOW_PAGES", str(window))
    else:
        monkeypatch.delenv("ARKS_RESIDENCY_WINDOW_PAGES", raising=False)
    cfg = get_config("tiny")
    defaults = dict(model="tiny", num_slots=1, max_cache_len=256,
                    prefill_buckets=(8, 16, 32), steps_per_dispatch=4,
                    prefill_chunk=16, kv_layout="paged", prefix_cache_mb=0)
    defaults.update(kw)
    eng = InferenceEngine(cfg, EngineConfig(**defaults), ByteTokenizer())
    if depth:
        assert eng._pipe_warm_wait(300) == "ready"
    return cfg, eng


def _drive(eng, n_steps=3000):
    for _ in range(n_steps):
        eng.step(block_s=0.01)
        if (eng.num_running == 0 and eng._queue.empty()
                and not eng._prefilling):
            break


def _collect(req):
    ids, lps, fin = [], [], None
    while True:
        out = req.outputs.get(timeout=300)
        ids.extend(out.token_ids)
        if out.logprobs:
            lps.extend(out.logprobs)
        if out.finished:
            fin = out
            break
    return ids, lps, fin


# Prompt (40 tokens, chunked prefill) + 70 decode tokens = 110-token
# final context: strictly larger than the windowed pool (6 pages x 16 =
# 96 tokens) while fitting the control's full 256-token table.
PROMPT_LEN, GEN = 40, 70


def _run_one(monkeypatch, *, window, depth, seeded):
    cfg, eng = _mk_engine(monkeypatch, window=window, depth=depth)
    prompt = [int(x) % cfg.vocab_size for x in range(3, 3 + PROMPT_LEN)]
    if seeded:
        sp = SamplingParams(max_tokens=GEN, temperature=0.8, top_p=0.9,
                            top_k=40, seed=17, ignore_eos=True)
    else:
        sp = SamplingParams(max_tokens=GEN, temperature=0.0,
                            ignore_eos=True, logprobs=2)
    req = Request("lc", prompt, sp)
    eng.add_request(req)
    _drive(eng)
    ids, lps, fin = _collect(req)
    return (ids, lps, fin.finish_reason), eng


@pytest.mark.parametrize("depth,seeded", [
    (0, False),
    pytest.param(0, True, marks=pytest.mark.slow),
    pytest.param(2, False, marks=pytest.mark.slow),
    pytest.param(2, True, marks=pytest.mark.slow),
], ids=["d0-greedy-lp", "d0-seeded", "d2-greedy-lp", "d2-seeded"])
def test_long_context_byte_identity_vs_big_pool_control(
        monkeypatch, depth, seeded):
    """The acceptance gate: a decode-grown context STRICTLY larger than
    the windowed engine's device pool emits a token stream (and logprob
    floats) byte-identical to a control engine whose pool holds the whole
    context resident — at pipeline depths 0 and 2."""
    got, eng = _run_one(monkeypatch, window=WINDOW, depth=depth,
                        seeded=seeded)
    base, _ = _run_one(monkeypatch, window=0, depth=depth, seeded=seeded)

    # The context really outgrew the windowed pool.
    final_len = PROMPT_LEN + len(got[0])
    pool_tokens = eng._alloc.num_pages * eng._page_size()
    assert final_len > pool_tokens, (final_len, pool_tokens)
    # ...and the span path actually ran.
    assert eng.metrics.residency_spans_total.total() > 0
    assert eng.metrics.residency_prefetch_pages_total.total() > 0

    assert got[0] == base[0], "token stream diverged from the control"
    assert got[2] == base[2] == "length"
    assert got[1] == base[1], "logprobs diverged from the control"


@pytest.mark.slow
def test_residency_slot_releases_pages_on_finish(monkeypatch):
    """After a windowed stream finishes, its staging + tail pages return
    to the allocator and the manager drops the slot — a fresh request
    then admits and completes on the same engine."""
    got, eng = _run_one(monkeypatch, window=WINDOW, depth=0, seeded=False)
    assert not eng._residency.slots
    assert eng._alloc.free_pages == eng._alloc.num_pages
    nxt = Request("post", [5, 6, 7], SamplingParams(
        max_tokens=4, temperature=0.0, ignore_eos=True))
    eng.add_request(nxt)
    _drive(eng)
    ids, _, fin = _collect(nxt)
    assert len(ids) == 4 and fin.finish_reason == "length"


def test_prompt_larger_than_window_is_rejected(monkeypatch):
    """Windowed residency streams DECODE-grown context; a prompt that
    cannot fit the resident window is rejected at admission with
    context_length_exceeded (not a crash deep inside the allocator)."""
    cfg, eng = _mk_engine(monkeypatch, window=WINDOW)
    too_long = [5] * (WINDOW * 16 + 1)  # page=prefill_chunk=16
    req = Request("big", [int(x) % cfg.vocab_size for x in too_long],
                  SamplingParams(max_tokens=2, temperature=0.0,
                                 ignore_eos=True))
    eng.add_request(req)
    _drive(eng)
    out = req.outputs.get(timeout=60)
    assert out.finished and out.finish_reason == "error"
    assert out.error == "context_length_exceeded"


def test_residency_config_validation(monkeypatch):
    """The window knob's failure modes are startup ValueErrors, not
    latent dispatch crashes: windows below 4 pages can't hold the
    2-tail + 2-staging-half layout; the span chain needs the Pallas
    ragged path; spec decode's draft cache has no windowed story."""
    cfg = get_config("tiny")

    def mk(**kw):
        defaults = dict(model="tiny", num_slots=1, max_cache_len=256,
                        prefill_buckets=(8, 16, 32), steps_per_dispatch=4,
                        prefill_chunk=16, kv_layout="paged",
                        prefix_cache_mb=0)
        defaults.update(kw)
        return InferenceEngine(cfg, EngineConfig(**defaults),
                               ByteTokenizer())

    monkeypatch.setenv("ARKS_MIXED_STEP", "1")
    monkeypatch.setenv("ARKS_ATTN_IMPL", "pallas")
    monkeypatch.setenv("ARKS_RESIDENCY_WINDOW_PAGES", "3")
    with pytest.raises(ValueError, match=">= 4"):
        mk()
    monkeypatch.setenv("ARKS_RESIDENCY_WINDOW_PAGES", "-1")
    with pytest.raises(ValueError, match=">= 0"):
        mk()
    monkeypatch.setenv("ARKS_RESIDENCY_WINDOW_PAGES", str(WINDOW))
    monkeypatch.setenv("ARKS_ATTN_IMPL", "xla")
    with pytest.raises(ValueError, match="pallas"):
        mk()
    monkeypatch.setenv("ARKS_ATTN_IMPL", "pallas")
    with pytest.raises(ValueError, match="speculative"):
        mk(draft_model="tiny", draft_len=3)
    # A window >= the logical table width is a no-op, not an error.
    monkeypatch.setenv("ARKS_RESIDENCY_WINDOW_PAGES", "64")
    eng = mk()
    assert eng._residency is None
    assert eng._alloc.num_pages == eng._max_pages * eng.ecfg.num_slots


def test_window_smaller_pool_is_allocated(monkeypatch):
    """The pool shrinks to num_slots * window pages while the logical
    tables keep the full max_cache_len width — the whole point: device
    HBM no longer scales with the model's context length."""
    cfg, eng = _mk_engine(monkeypatch, window=WINDOW, num_slots=2)
    assert eng._alloc.num_pages == 2 * WINDOW
    assert eng._tables.shape == (2, eng._max_pages)
    assert eng._max_pages == 256 // 16
