"""Chaos suite: fault-injected serving must preserve every innocent
stream byte-for-byte.

Scripted ARKS_FAULT_INJECT scenarios kill scheduler phases mid-run on the
slot and paged/mixed engines at pipeline depths 0 and 2, and every
surviving stream's token sequence is asserted IDENTICAL to a fault-free
run of the same engine (no duplicated, dropped, or changed tokens) while
the recovery metrics advance.  The scripted subset here is tier-1; the
randomized sweep at the bottom is additionally marked slow.

The engines are driven synchronously through the same
step/_recover_from_fault contract the engine thread runs (_run_loop), so
faults land deterministically.
"""

import os
import random
import time

import pytest

from arks_tpu.engine import EngineConfig, InferenceEngine, Request, SamplingParams
from arks_tpu.engine.faults import FaultInjector, InjectedFault, Watchdog
from arks_tpu.engine.paged import chain_digests
from arks_tpu.engine.tokenizer import ByteTokenizer
from arks_tpu.models import get_config

pytestmark = pytest.mark.chaos

SLOT = ("0", {})
MIXED = ("auto", dict(prefill_chunk=16, kv_layout="paged"))
# Speculative engines ride the mixed scheduler (draft+verify inside the
# mixed dispatch) and join token-replay recovery like everyone else.
SPEC = ("auto", dict(prefill_chunk=16, kv_layout="paged",
                     draft_model="tiny", draft_len=3))


def _mk_engine(monkeypatch, depth=0, mixed="0", inject=None, retries=None,
               **kw):
    monkeypatch.setenv("ARKS_PIPELINE_DEPTH", str(depth))
    monkeypatch.setenv("ARKS_MIXED_STEP", mixed)
    if inject is None:
        monkeypatch.delenv("ARKS_FAULT_INJECT", raising=False)
    else:
        monkeypatch.setenv("ARKS_FAULT_INJECT", inject)
    if retries is None:
        monkeypatch.delenv("ARKS_FAULT_RETRIES", raising=False)
    else:
        monkeypatch.setenv("ARKS_FAULT_RETRIES", str(retries))
    cfg = get_config("tiny")
    defaults = dict(model="tiny", num_slots=2, max_cache_len=64,
                    prefill_buckets=(8, 16, 32), steps_per_dispatch=4)
    defaults.update(kw)
    eng = InferenceEngine(cfg, EngineConfig(**defaults), ByteTokenizer())
    if depth:
        assert eng._pipe_warm_wait(300) == "ready"
    return cfg, eng


def _drive(eng, n_steps=1500):
    """The engine thread's own step/recover contract, synchronously."""
    for _ in range(n_steps):
        try:
            eng.step(block_s=0.01)
        except Exception as e:  # noqa: BLE001 — routed exactly like _run_loop
            eng._recover_from_fault(e)
        if (eng.num_running == 0 and eng._queue.empty()
                and not eng._prefilling and not eng._awaiting_fetch
                and not eng._awaiting_restore and eng.state == "serving"):
            break


def _collect(req, timeout=120):
    ids, fin = [], None
    while True:
        out = req.outputs.get(timeout=timeout)
        ids.extend(out.token_ids)
        if out.finished:
            fin = out
            break
    return ids, fin


def _workload(cfg):
    """Greedy + seeded-sampled requests, mixed prompt lengths."""
    prompts = [[5, 6, 7], [9] * 5]
    reqs = []
    for i, p in enumerate(prompts):
        sp = SamplingParams(max_tokens=14,
                           temperature=0.0 if i % 2 == 0 else 0.9,
                           top_p=0.9, top_k=40, seed=21 + i, ignore_eos=True)
        reqs.append(Request(f"r{i}", [int(x) % cfg.vocab_size for x in p], sp))
    return reqs


def _run(monkeypatch, depth, mixed, kw, inject=None, retries=None):
    cfg, eng = _mk_engine(monkeypatch, depth, mixed, inject=inject,
                          retries=retries, **kw)
    reqs = _workload(cfg)
    for r in reqs:
        eng.add_request(r)
    _drive(eng)
    return [_collect(r) for r in reqs], eng


@pytest.mark.parametrize("depth", [0, 2])
@pytest.mark.parametrize("mixed,kw", [SLOT, MIXED],
                         ids=["slot", "paged-mixed"])
def test_decode_fault_recovers_all_streams_byte_identical(
        monkeypatch, depth, mixed, kw):
    """An injected decode-dispatch fault mid-run must recover EVERY
    in-flight stream byte-identically (same tokens, same finish reasons)
    on both engine layouts and at pipeline depths 0 and 2, with the fault
    and recovery metrics advancing."""
    base, _ = _run(monkeypatch, depth, mixed, kw)
    got, eng = _run(monkeypatch, depth, mixed, kw, inject="decode:3:runtime")
    assert [f.finish_reason for _, f in got] == ["length", "length"]
    assert got == base, "surviving streams diverged from the fault-free run"
    faults = sum(eng.metrics.engine_faults_total._values.values())
    assert faults == 1
    recovered = sum(eng.metrics.requests_recovered_total._values.values())
    assert recovered == 2
    assert sum(eng.metrics.requests_quarantined_total._values.values()) == 0
    assert eng.metrics.engine_recovery_seconds._data, \
        "recovery latency never observed"
    assert eng.state == "serving"


@pytest.mark.parametrize("depth", [0, 2])
def test_spec_fault_recovers_all_streams_byte_identical(monkeypatch, depth):
    """A fault injected in the SPEC phase (the spec-mixed dispatch issue,
    or the pipelined spec issue at depth 2) must recover every in-flight
    stream byte-identically via token replay — spec engines joined the
    recovery contract when the fused spec loop was retired."""
    base, _ = _run(monkeypatch, depth, *SPEC)
    got, eng = _run(monkeypatch, depth, *SPEC, inject="spec:3:runtime")
    assert [f.finish_reason for _, f in got] == ["length", "length"]
    assert got == base, "surviving spec streams diverged from the fault-free run"
    faults = sum(eng.metrics.engine_faults_total._values.values())
    assert faults == 1
    assert sum(eng.metrics.requests_recovered_total._values.values()) == 2
    assert sum(eng.metrics.requests_quarantined_total._values.values()) == 0
    assert eng.state == "serving"


def test_spec_repeated_fault_quarantines_only_the_culprit(monkeypatch):
    """Spec phase fault -> everyone replays; the FIRST replay operation
    then faults too -> that request fails ALONE while the other spec
    stream finishes byte-identical to the fault-free run."""
    base, _ = _run(monkeypatch, 0, *SPEC)
    got, eng = _run(monkeypatch, 0, *SPEC,
                    inject="spec:3:runtime,replay:1:runtime")
    reasons = [f.finish_reason for _, f in got]
    assert reasons.count("error") == 1, reasons
    errs = [f for _, f in got if f.finish_reason == "error"]
    assert errs[0].error.startswith("engine_fault")
    base_streams = {f.request_id: (ids, f.finish_reason) for ids, f in base}
    for ids, f in got:
        if f.finish_reason != "error":
            assert (ids, f.finish_reason) == base_streams[f.request_id], \
                "survivor stream diverged from the fault-free run"
    assert sum(eng.metrics.requests_quarantined_total._values.values()) == 1
    assert eng.state == "serving"


@pytest.mark.parametrize("mixed,kw", [SLOT, MIXED],
                         ids=["slot", "paged-mixed"])
def test_repeated_fault_quarantines_only_the_culprit(monkeypatch, mixed, kw):
    """decode fault -> everyone replays; the FIRST replay operation then
    faults too -> that request has exhausted ARKS_FAULT_RETRIES=1 and
    fails ALONE with finish_reason="error"/engine_fault, while the other
    stream still finishes byte-identical to the fault-free run."""
    base, _ = _run(monkeypatch, 0, mixed, kw)
    got, eng = _run(monkeypatch, 0, mixed, kw,
                    inject="decode:3:runtime,replay:1:runtime")
    reasons = [f.finish_reason for _, f in got]
    assert reasons.count("error") == 1, reasons
    errs = [f for _, f in got if f.finish_reason == "error"]
    assert errs[0].error.startswith("engine_fault")
    survivors = [(ids, f.finish_reason) for ids, f in got
                 if f.finish_reason != "error"]
    base_by_rid = {f.request_id: (ids, f.finish_reason) for ids, f in base}
    for ids, fr in survivors:
        assert (ids, fr) in [base_by_rid[rid] for rid in base_by_rid], \
            "survivor stream diverged from the fault-free run"
    assert sum(eng.metrics.requests_quarantined_total._values.values()) == 1
    assert eng.state == "serving"


def test_zero_retry_budget_fails_culprits_immediately(monkeypatch):
    """ARKS_FAULT_RETRIES=0: the faulting dispatch's culprits fail at the
    first fault (no replay), and the engine keeps serving new work."""
    got, eng = _run(monkeypatch, 0, *SLOT, inject="decode:3:runtime",
                    retries=0)
    reasons = [f.finish_reason for _, f in got]
    assert reasons == ["error", "error"]
    assert all(f.error.startswith("engine_fault") for _, f in got)
    assert sum(eng.metrics.requests_quarantined_total._values.values()) == 2
    # The engine is healthy afterwards: a fresh request completes.
    nxt = Request("post", [4, 4, 4], SamplingParams(
        max_tokens=4, temperature=0.0, ignore_eos=True))
    eng.add_request(nxt)
    _drive(eng)
    ids, fin = _collect(nxt)
    assert fin.finish_reason == "length" and len(ids) == 4


def test_admit_fault_requeues_requests(monkeypatch):
    """A fault inside the fused admission dispatch must re-queue the
    batch's requests (nothing was emitted yet) and the streams come out
    byte-identical to a fault-free run — pinned engine-assigned seeds."""
    base, _ = _run(monkeypatch, 0, *SLOT)
    got, eng = _run(monkeypatch, 0, *SLOT, inject="admit:1:runtime")
    assert got == base
    assert sum(eng.metrics.requests_recovered_total._values.values()) >= 1


def _tenant_workload(cfg):
    """Two tenants' worth of seeded streams for the fair-admission
    chaos scenarios — enough depth that the WDRR pick point fires with
    requests still waiting behind it."""
    reqs = []
    for i in range(3):
        reqs.append(Request(f"a{i}", [5, 6, 7], SamplingParams(
            max_tokens=8, temperature=0.9, top_p=0.9, seed=41 + i,
            ignore_eos=True), tenant="ns/a"))
        reqs.append(Request(f"b{i}", [9] * 5, SamplingParams(
            max_tokens=8, temperature=0.0, ignore_eos=True),
            tenant="ns/b"))
    return reqs


def _run_tenants(monkeypatch, inject=None, retries=None):
    monkeypatch.setenv("ARKS_FAIR", "1")
    cfg, eng = _mk_engine(monkeypatch, 0, "0", inject=inject,
                          retries=retries)
    reqs = _tenant_workload(cfg)
    for r in reqs:
        eng.add_request(r)
    _drive(eng)
    return [_collect(r) for r in reqs], eng


def test_admit_fair_fault_requeues_through_the_fair_queue(monkeypatch):
    """A fault at the WDRR pick point ("admit_fair" phase): the popped
    request re-queues through the fair queue (nothing was emitted yet)
    and EVERY stream — both tenants — comes out byte-identical to the
    fault-free run."""
    base, _ = _run_tenants(monkeypatch)
    got, eng = _run_tenants(monkeypatch, inject="admit_fair:2:runtime")
    assert got == base, \
        "streams diverged after the admit_fair fault"
    assert sum(eng.metrics.engine_faults_total._values.values()) == 1
    assert eng.metrics.engine_faults_total.get(
        phase="admit_fair", kind="injected") == 1
    assert sum(eng.metrics.requests_quarantined_total._values.values()) == 0
    assert eng.state == "serving"


def test_admit_fair_repeated_fault_quarantines_only_the_culprit(
        monkeypatch):
    """Zero retry budget: the admit_fair fault fails its ONE popped
    request (the sole culprit), every other stream — same tenant and
    the other tenant alike — finishes byte-identical to the fault-free
    run, and the fair queue keeps serving."""
    base, _ = _run_tenants(monkeypatch)
    got, eng = _run_tenants(monkeypatch, inject="admit_fair:2:runtime",
                            retries=0)
    reasons = [f.finish_reason for _, f in got]
    assert reasons.count("error") == 1, reasons
    errs = [f for _, f in got if f.finish_reason == "error"]
    assert errs[0].error.startswith("engine_fault")
    base_by_rid = {f.request_id: (ids, f.finish_reason) for ids, f in base}
    for ids, f in got:
        if f.finish_reason != "error":
            assert (ids, f.finish_reason) == base_by_rid[f.request_id], \
                "survivor stream diverged from the fault-free run"
    assert sum(eng.metrics.requests_quarantined_total._values.values()) == 1
    assert eng.state == "serving"


def test_chunk_fault_on_long_prompt_is_isolated(monkeypatch):
    """A chunked-prefill dispatch fault is attributed to its ONE request:
    within budget it recovers; the co-resident decoding stream is
    byte-identical either way."""
    cfg, eng0 = _mk_engine(monkeypatch, 0, "0")
    short = Request("short", [5, 6, 7], SamplingParams(
        max_tokens=14, temperature=0.0, ignore_eos=True))
    # Beyond the largest one-shot bucket (32) -> chunked prefill.
    long_r = Request("long", [7] * 40, SamplingParams(
        max_tokens=6, temperature=0.0, ignore_eos=True))
    eng0.add_request(short)
    eng0.add_request(long_r)
    _drive(eng0)
    base = [_collect(short), _collect(long_r)]

    cfg, eng = _mk_engine(monkeypatch, 0, "0", inject="chunk:1:runtime")
    short2 = Request("short", [5, 6, 7], short.params)
    long2 = Request("long", [7] * 40, long_r.params)
    eng.add_request(short2)
    eng.add_request(long2)
    _drive(eng)
    got = [_collect(short2), _collect(long2)]
    assert got == base
    assert sum(eng.metrics.requests_recovered_total._values.values()) >= 1


def test_abort_during_recovery_wins_over_replay(monkeypatch):
    """An abort that races the fault/recovery window must finish the
    request as "abort" — never replay it back to life."""
    cfg, eng = _mk_engine(monkeypatch, 0, "0")
    victim = Request("v", [5, 6, 7], SamplingParams(
        max_tokens=10_000, temperature=0.0, ignore_eos=True))
    other = Request("o", [9, 9], SamplingParams(
        max_tokens=6, temperature=0.0, ignore_eos=True))
    eng.add_request(victim)
    eng.add_request(other)
    for _ in range(60):
        try:
            eng.step(block_s=0.01)
        except Exception as e:  # noqa: BLE001
            eng._recover_from_fault(e)
        if eng._slots:
            break
    assert eng._slots, "nothing admitted"
    # Raise the abort, then force a step fault before the scheduler can
    # consume it on the normal path.
    eng.abort("v")
    eng._faults.arm("decode:1:runtime")
    _drive(eng)
    _, fin_v = _collect(victim)
    _, fin_o = _collect(other)
    assert fin_v.finish_reason == "abort"
    assert fin_o.finish_reason == "length"
    with eng._abort_lock:
        assert "v" not in eng._aborted


def test_fault_injector_spec_parsing():
    inj = FaultInjector("decode:2:runtime, replay:1:oom")
    inj.fire("decode")
    with pytest.raises(InjectedFault):
        inj.fire("decode")
    inj.fire("decode")  # each spec entry fires at most once
    with pytest.raises(InjectedFault, match="RESOURCE_EXHAUSTED"):
        inj.fire("replay")
    for bad in ("decode:x:runtime", "decode:0:runtime", "decode:1:nope",
                "decode:1"):
        with pytest.raises(ValueError):
            FaultInjector(bad)
    assert not FaultInjector("").active


def test_watchdog_escalates_on_wedged_step(monkeypatch):
    """A step heartbeat older than the deadline flips the wedged callback
    and escalates through the exit fn with code 70."""
    import time as _time
    events = []
    hb = ("decode", _time.monotonic() - 10.0)
    wd = Watchdog(0.1, lambda: hb, lambda phase, age: events.append(phase),
                  exit_fn=lambda code: events.append(code))
    wd.start()
    deadline = _time.monotonic() + 5
    while len(events) < 2 and _time.monotonic() < deadline:
        _time.sleep(0.02)
    wd.stop()
    assert events == ["decode", 70]


def test_watchdog_quiet_while_healthy():
    import time as _time
    fired = []
    wd = Watchdog(0.2, lambda: None, lambda *a: fired.append(a),
                  exit_fn=lambda code: fired.append(code))
    wd.start()
    _time.sleep(0.6)
    wd.stop()
    assert not fired


def test_engine_state_gauge_and_readiness_mapping(monkeypatch):
    """The engine_state gauge tracks the recovery window (0 -> 1 -> 0)."""
    cfg, eng = _mk_engine(monkeypatch, 0, "0", inject="decode:2:runtime")
    r = Request("r", [5, 6], SamplingParams(
        max_tokens=8, temperature=0.0, ignore_eos=True))
    eng.add_request(r)
    states = set()
    for _ in range(400):
        try:
            eng.step(block_s=0.01)
        except Exception as e:  # noqa: BLE001
            eng._recover_from_fault(e)
            states.add(eng.state)
        if (eng.num_running == 0 and eng._queue.empty()
                and not eng._prefilling and eng.state == "serving"):
            break
    _collect(r)
    assert "recovering" in states
    assert eng.state == "serving"
    assert eng.metrics.engine_state.get() == 0


@pytest.mark.slow
@pytest.mark.parametrize("mixed,kw", [SLOT, MIXED],
                         ids=["slot", "paged-mixed"])
def test_randomized_chaos_sweep(monkeypatch, mixed, kw):
    """Randomized injection over phases/offsets: per-stream integrity must
    hold in EVERY round — each stream either matches the fault-free run
    exactly or fails alone with an engine_fault error; the engine always
    returns to "serving"."""
    base, _ = _run(monkeypatch, 0, mixed, kw)
    base_by_rid = {fin.request_id: (ids, fin.finish_reason)
                   for ids, fin in base}
    rng = random.Random(1234)
    phases = ["decode", "resolve", "admit", "admit_fair", "chunk",
              "replay", "pages"]
    for round_i in range(6):
        spec = ",".join(
            f"{rng.choice(phases)}:{rng.randint(1, 6)}:runtime"
            for _ in range(rng.randint(1, 3)))
        got, eng = _run(monkeypatch, 0, mixed, kw, inject=spec)
        for ids, fin in got:
            if fin.finish_reason == "error":
                assert fin.error.startswith("engine_fault"), \
                    f"round {round_i} ({spec}): unexpected error {fin.error}"
                continue
            assert (ids, fin.finish_reason) == base_by_rid[fin.request_id], \
                f"round {round_i} ({spec}): stream integrity violated"
        assert eng.state == "serving", f"round {round_i} ({spec})"


class _RecordingDispatcher:
    def __init__(self):
        self.ops = []

    def broadcast(self, op, payload):
        self.ops.append((op, payload))


def test_recover_op_reaches_followers(monkeypatch):
    """Multihost: a fault broadcasts a "recover" op (surviving-request
    manifest) followed by "reset", and the replayed re-admission rides the
    ordinary op stream — followers rebuild from the leader's manifest."""
    cfg, eng = _mk_engine(monkeypatch, 0, "0", inject="decode:2:runtime")
    eng.dispatcher = _RecordingDispatcher()
    r = Request("m0", [5, 6, 7], SamplingParams(
        max_tokens=8, temperature=0.0, ignore_eos=True))
    eng.add_request(r)
    _drive(eng)
    _collect(r)
    ops = [op for op, _ in eng.dispatcher.ops]
    assert "recover" in ops and "reset" in ops
    assert ops.index("recover") < ops.index("reset")
    recover_payload = next(p for op, p in eng.dispatcher.ops
                           if op == "recover")
    assert [m[0] for m in recover_payload["manifest"]] == ["m0"]
    # The replay re-admission was mirrored too (ops after the reset).
    after = ops[ops.index("reset") + 1:]
    assert any(op in ("admit_batch", "chunk", "chunk_paged", "mixed")
               for op in after)


def test_follower_applies_recover_op(monkeypatch):
    """DispatchFollower handles the recover op: pipeline replay state
    drops so the next decode_pipe must be fresh, and the manifest is
    accepted without touching device state."""
    from arks_tpu.engine.multihost import DispatchFollower
    cfg, eng = _mk_engine(monkeypatch, 0, "0")
    follower = DispatchFollower.__new__(DispatchFollower)
    follower.engine = eng
    import jax as _jax
    follower._jax = _jax
    follower._pipe_state = ("stale",)
    follower._pipe_cols = ("stale",)
    import jax.numpy as _jnp
    follower._apply(eng, _jax, _jnp, "recover",
                    {"manifest": [("r0", 3, 5)], "phase": "decode",
                     "kind": "injected"})
    assert follower._pipe_state is None and follower._pipe_cols is None


def _restore_scenario(monkeypatch, inject=None, retries=None):
    """Shared-prefix workload on the tiered cache: a warm prompt, churn
    that evicts it into the host tier, a co-resident decoding stream,
    then the warm prompt again — whose admission goes through the tier-1
    RESTORE path (the injectable "restore" phase)."""
    monkeypatch.setenv("ARKS_PREFIX_HOST_MB", "64")
    cfg, eng = _mk_engine(monkeypatch, 0, "auto", inject=inject,
                          retries=retries, prefill_chunk=16,
                          kv_layout="paged", prefix_cache_mb=0)
    assert eng._host is not None
    warm = [int(x) % cfg.vocab_size for x in range(3, 36)]  # 2 pages + tail
    outs = []

    def run_one(req):
        eng.add_request(req)
        _drive(eng)
        return req

    # Warm the prefix, then churn it out of the device index (spilled).
    run_one(Request("w1", warm, SamplingParams(
        max_tokens=4, temperature=0.0, ignore_eos=True)))
    for i in range(5):
        run_one(Request(f"ch{i}", [(9 + i) % cfg.vocab_size] * 33,
                        SamplingParams(max_tokens=3, temperature=0.0,
                                       ignore_eos=True)))
    # A long-lived innocent stream decodes while the restore happens.
    bystander = Request("by", [5, 6, 7], SamplingParams(
        max_tokens=20, temperature=0.9, top_p=0.9, top_k=40, seed=11,
        ignore_eos=True))
    eng.add_request(bystander)
    for _ in range(60):
        try:
            eng.step(block_s=0.01)
        except Exception as e:  # noqa: BLE001 — routed like _run_loop
            eng._recover_from_fault(e)
        if eng._slots:
            break
    victim = Request("w2", warm, SamplingParams(
        max_tokens=4, temperature=0.0, ignore_eos=True))
    eng.add_request(victim)
    _drive(eng)
    outs = [_collect(bystander), _collect(victim)]
    return outs, eng


def test_restore_fault_is_isolated_to_the_restoring_request(monkeypatch):
    """A fault injected at the tier-1 restore phase must recover: within
    the retry budget the restoring request re-queues (its retry hits the
    host tier again — it survives the device reset), and the co-resident
    decoding stream is byte-identical to the fault-free run."""
    base, beng = _restore_scenario(monkeypatch)
    assert beng.metrics.prefix_restore_blocks_total.total() > 0, \
        "scenario never exercised the restore path"
    got, eng = _restore_scenario(monkeypatch, inject="restore:1:runtime")
    assert [f.finish_reason for _, f in got] == ["length", "length"]
    assert got == base, "streams diverged after the restore fault"
    assert sum(eng.metrics.engine_faults_total._values.values()) == 1
    assert eng.metrics.engine_faults_total.get(
        phase="restore", kind="injected") == 1
    assert sum(eng.metrics.requests_quarantined_total._values.values()) == 0
    assert eng.state == "serving"


def test_restore_fault_quarantines_only_the_culprit(monkeypatch):
    """With a zero retry budget, the restore fault fails the restoring
    request ALONE (finish_reason="error"/engine_fault); the innocent
    decoding stream still finishes byte-identical to the fault-free
    run."""
    base, _ = _restore_scenario(monkeypatch)
    got, eng = _restore_scenario(monkeypatch, inject="restore:1:runtime",
                                 retries=0)
    (by_ids, by_fin), (_, v_fin) = got
    assert v_fin.finish_reason == "error"
    assert v_fin.error.startswith("engine_fault")
    assert (by_ids, by_fin.finish_reason) == (base[0][0], "length")
    assert sum(eng.metrics.requests_quarantined_total._values.values()) == 1
    assert eng.state == "serving"


def _disk_scenario(monkeypatch, depth, ddir, inject=None, retries=None,
                   wait_disk=True):
    """Tier-2 traffic on the tiered cache: a warm prompt spills into the
    host tier under churn, a capacity squeeze evicts it into the DISK
    drain (the injectable "disk_spill" phase), and the warm prompt's
    return parks in the fetch path whose unpark is the injectable
    "peer_fetch" phase."""
    monkeypatch.setenv("ARKS_PREFIX_HOST_MB", "64")
    monkeypatch.setenv("ARKS_PREFIX_DISK_MB", "8")
    monkeypatch.setenv("ARKS_PREFIX_DISK_DIR", str(ddir))
    cfg, eng = _mk_engine(monkeypatch, depth, "auto", inject=inject,
                          retries=retries, prefill_chunk=16,
                          kv_layout="paged", prefix_cache_mb=0)
    assert eng._disk is not None
    warm = [int(x) % cfg.vocab_size for x in range(3, 36)]  # 2 pages + tail

    def run_one(req):
        eng.add_request(req)
        _drive(eng)
        return req

    # Warm the prefix, churn it out of the device index into the host
    # tier, then squeeze the host tier to its current footprint so the
    # NEXT churn round evicts the (LRU) warm blocks into the disk drain.
    run_one(Request("w1", warm, SamplingParams(
        max_tokens=4, temperature=0.0, ignore_eos=True)))
    for i in range(5):
        run_one(Request(f"ch{i}", [(9 + i) % cfg.vocab_size] * 33,
                        SamplingParams(max_tokens=3, temperature=0.0,
                                       ignore_eos=True)))
    eng._host.capacity = eng._host.bytes_used
    for i in range(3):
        run_one(Request(f"cv{i}", [(17 + i) % cfg.vocab_size] * 33,
                        SamplingParams(max_tokens=3, temperature=0.0,
                                       ignore_eos=True)))
    if wait_disk:
        # The spill drain is step-driven and the file write is async on
        # the writer thread — give both a bounded moment.
        digests = chain_digests(warm, 16, 2)
        deadline = time.monotonic() + 30
        while (not all(eng._disk.has(d) for d in digests)
               and time.monotonic() < deadline):
            try:
                eng.step(block_s=0.01)
            except Exception as e:  # noqa: BLE001 — routed like _run_loop
                eng._recover_from_fault(e)
            time.sleep(0.01)
        assert all(eng._disk.has(d) for d in digests), \
            "warm blocks never reached the disk tier"
    # A long-lived innocent stream decodes while the fetch happens.
    bystander = Request("by", [5, 6, 7], SamplingParams(
        max_tokens=20, temperature=0.9, top_p=0.9, top_k=40, seed=11,
        ignore_eos=True))
    eng.add_request(bystander)
    for _ in range(60):
        try:
            eng.step(block_s=0.01)
        except Exception as e:  # noqa: BLE001 — routed like _run_loop
            eng._recover_from_fault(e)
        if eng._slots:
            break
    victim = Request("w2", warm, SamplingParams(
        max_tokens=4, temperature=0.0, ignore_eos=True))
    eng.add_request(victim)
    _drive(eng)
    outs = [_collect(bystander), _collect(victim)]
    return outs, eng


@pytest.mark.parametrize("depth", [0, 2])
def test_disk_spill_fault_leaves_streams_intact(monkeypatch, depth,
                                                tmp_path):
    """A fault in the tier-2 spill drain serves no specific request:
    even with a ZERO retry budget nobody is quarantined, every stream
    finishes byte-identical to the fault-free run, and the engine keeps
    serving — the warm blocks simply never reach disk (dropped spill,
    re-prefill on return)."""
    base, beng = _disk_scenario(monkeypatch, depth, tmp_path / "b")
    assert beng.metrics.prefix_peer_fetch_blocks_total.get(
        source="disk") == 2, "scenario never exercised the disk tier"
    got, eng = _disk_scenario(monkeypatch, depth, tmp_path / "f",
                              inject="disk_spill:1:runtime", retries=0,
                              wait_disk=False)
    assert [f.finish_reason for _, f in got] == ["length", "length"]
    assert got == base, "streams diverged after the disk-spill fault"
    assert eng.metrics.engine_faults_total.get(
        phase="disk_spill", kind="injected") == 1
    assert sum(eng.metrics.requests_quarantined_total._values.values()) == 0
    assert eng.state == "serving"


@pytest.mark.parametrize("depth", [0, 2])
def test_fetch_resolve_fault_recovers_within_budget(monkeypatch, depth,
                                                    tmp_path):
    """A fault at the fetch unpark ("peer_fetch" phase): within the
    retry budget the fetching request re-queues, its retry re-parks on
    the disk tier and restores, and both it and the co-resident decoding
    stream finish byte-identical to the fault-free run."""
    base, beng = _disk_scenario(monkeypatch, depth, tmp_path / "b")
    assert beng.metrics.prefix_peer_fetch_blocks_total.get(
        source="disk") == 2, "scenario never exercised the disk fetch"
    got, eng = _disk_scenario(monkeypatch, depth, tmp_path / "f",
                              inject="peer_fetch:1:runtime")
    assert [f.finish_reason for _, f in got] == ["length", "length"]
    assert got == base, "streams diverged after the fetch fault"
    assert eng.metrics.engine_faults_total.get(
        phase="peer_fetch", kind="injected") == 1
    assert sum(eng.metrics.requests_quarantined_total._values.values()) == 0
    assert eng.state == "serving"


def test_fetch_resolve_fault_quarantines_only_the_fetcher(monkeypatch,
                                                          tmp_path):
    """With a zero retry budget the fetch fault fails the fetching
    request ALONE (finish_reason="error"/engine_fault); the innocent
    decoding stream still finishes byte-identical to the fault-free
    run."""
    base, _ = _disk_scenario(monkeypatch, 0, tmp_path / "b")
    got, eng = _disk_scenario(monkeypatch, 0, tmp_path / "f",
                              inject="peer_fetch:1:runtime", retries=0)
    (by_ids, by_fin), (_, v_fin) = got
    assert v_fin.finish_reason == "error"
    assert v_fin.error.startswith("engine_fault")
    assert (by_ids, by_fin.finish_reason) == (base[0][0], "length")
    assert sum(eng.metrics.requests_quarantined_total._values.values()) == 1
    assert eng.state == "serving"


def _residency_scenario(monkeypatch, depth, inject=None, retries=None):
    """Windowed-residency traffic: a long decode stream outgrows the
    6-page resident window (pool = num_slots * window) and engages the
    span-streaming path — the injectable "residency" phase — while an
    innocent seeded stream decodes alongside on the classic mixed path."""
    monkeypatch.setenv("ARKS_RESIDENCY_WINDOW_PAGES", "6")
    monkeypatch.setenv("ARKS_ATTN_IMPL", "pallas")
    cfg, eng = _mk_engine(monkeypatch, depth, "1", inject=inject,
                          retries=retries, prefill_chunk=16,
                          kv_layout="paged", prefix_cache_mb=0,
                          max_cache_len=256)
    # 40-token prompt + 70 decode tokens = 110 > the 96-token resident
    # budget: the stream engages mid-decode and finishes windowed.
    long_r = Request("win", [int(x) % cfg.vocab_size
                             for x in range(3, 43)],
                     SamplingParams(max_tokens=70, temperature=0.0,
                                    ignore_eos=True))
    bystander = Request("by", [5, 6, 7], SamplingParams(
        max_tokens=80, temperature=0.9, top_p=0.9, top_k=40, seed=11,
        ignore_eos=True))
    eng.add_request(long_r)
    eng.add_request(bystander)
    _drive(eng, n_steps=3000)
    outs = [_collect(long_r), _collect(bystander)]
    return outs, eng


@pytest.mark.slow
@pytest.mark.parametrize("depth", [0, 2])
def test_residency_fault_recovers_all_streams_byte_identical(
        monkeypatch, depth):
    """A fault injected at the windowed span step ("residency" phase):
    within the retry budget the engaged stream token-replays (re-growing
    back through engagement), the co-resident classic-path stream
    replays too, and BOTH finish byte-identical to the fault-free run at
    pipeline depths 0 and 2."""
    base, beng = _residency_scenario(monkeypatch, depth)
    assert beng.metrics.residency_spans_total.total() > 0, \
        "scenario never engaged the windowed path"
    got, eng = _residency_scenario(monkeypatch, depth,
                                   inject="residency:1:runtime")
    assert [f.finish_reason for _, f in got] == ["length", "length"]
    assert got == base, "streams diverged after the residency fault"
    assert eng.metrics.engine_faults_total.get(
        phase="residency", kind="injected") == 1
    assert sum(eng.metrics.requests_quarantined_total._values.values()) == 0
    assert eng.state == "serving"


@pytest.mark.slow
def test_residency_fault_quarantines_only_the_engaged_culprit(monkeypatch):
    """With a zero retry budget the residency fault fails the ENGAGED
    stream alone (finish_reason="error"/engine_fault) — the culprit set
    is the window-engaged slots, never the co-resident classic-path
    stream, which finishes byte-identical to the fault-free run."""
    base, _ = _residency_scenario(monkeypatch, 0)
    got, eng = _residency_scenario(monkeypatch, 0,
                                   inject="residency:1:runtime", retries=0)
    (_, w_fin), (by_ids, by_fin) = got
    assert w_fin.finish_reason == "error"
    assert w_fin.error.startswith("engine_fault")
    assert (by_ids, by_fin.finish_reason) == (base[1][0], "length")
    assert sum(eng.metrics.requests_quarantined_total._values.values()) == 1
    assert eng.state == "serving"


def test_decode_fault_while_another_request_prefills(monkeypatch):
    """A decode fault with a long prompt mid-chunked-prefill: the decoding
    stream token-replays, the prefilling one re-runs from the top, both
    byte-identical to the fault-free run."""
    def scenario(inject):
        # prefill_chunk=16: the 40-token prompt needs 3 chunk dispatches,
        # so the injected decode fault lands while it is MID-PREFILL.
        cfg, eng = _mk_engine(monkeypatch, 0, "0", inject=inject,
                              prefill_chunk=16)
        dec = Request("dec", [5, 6, 7], SamplingParams(
            max_tokens=20, temperature=0.9, top_p=0.9, top_k=40, seed=5,
            ignore_eos=True))
        long_r = Request("long", [7] * 40, SamplingParams(
            max_tokens=6, temperature=0.0, ignore_eos=True))
        eng.add_request(dec)
        eng.add_request(long_r)
        for _ in range(40):
            try:
                eng.step(block_s=0.01)
            except Exception as e:  # noqa: BLE001
                eng._recover_from_fault(e)
            if inject is None and eng._prefilling and eng._slots:
                break  # confirm the overlap window exists fault-free
        _drive(eng)
        return [_collect(dec), _collect(long_r)], eng

    base, _ = scenario(None)
    got, eng = scenario("decode:2:runtime")
    assert got == base
    assert sum(eng.metrics.requests_recovered_total._values.values()) == 2
    assert eng.state == "serving"


# ---- elastic resize (live topology change) ---------------------------


def _drive_elastic(eng, n_steps=3000):
    """_drive, but quiet also requires the resize machinery to be done:
    no in-flight resize request and no swapped victims awaiting restore
    (the plain quiet check reads num_running == 0 at the drained
    boundary and would bail mid-resize)."""
    for _ in range(n_steps):
        try:
            eng.step(block_s=0.01)
        except Exception as e:  # noqa: BLE001 — routed like _run_loop
            eng._recover_from_fault(e)
        if (eng._resize_req is None and not eng._swapped
                and not eng._swap_pending and not eng._spills
                and eng.num_running == 0 and eng._queue.empty()
                and not eng._prefilling and not eng._awaiting_fetch
                and not eng._awaiting_restore and eng.state == "serving"):
            break


def _resize_scenario(monkeypatch, depth, inject=None, retries=None,
                     resize=True, tp=2):
    """Mid-stream live resize: two ALL-GREEDY streams decode on the
    paged-mixed engine, a tp1 -> tp{tp} resize posts once both hold
    slots, and the drive runs the drain/reshard/resume machinery to
    completion.  Greedy only: byte-identity across a TP change holds
    for argmax streams (sampled streams are distribution-exact, not
    byte-exact — the psum reduction order shifts with the mesh)."""
    cfg, eng = _mk_engine(monkeypatch, depth, "auto", inject=inject,
                          retries=retries, prefill_chunk=16,
                          kv_layout="paged")
    reqs = [Request(f"r{i}", [int(x) % cfg.vocab_size for x in p],
                    SamplingParams(max_tokens=14, temperature=0.0,
                                   ignore_eos=True))
            for i, p in enumerate([[5, 6, 7], [9] * 5])]
    for r in reqs:
        eng.add_request(r)
    for _ in range(60):
        try:
            eng.step(block_s=0.01)
        except Exception as e:  # noqa: BLE001 — routed like _run_loop
            eng._recover_from_fault(e)
        if eng._slots:
            break
    assert eng._slots, "streams never reached slots before the resize"
    hold = eng.request_resize(tensor_parallel=tp) if resize else None
    _drive_elastic(eng, n_steps=3000)
    outs = [_collect(r) for r in reqs]
    return outs, eng, hold


@pytest.mark.parametrize("depth", [0, 2])
def test_live_resize_preserves_streams_byte_identical(monkeypatch, depth):
    """A tp1 -> tp2 live resize posted MID-STREAM: both greedy streams
    finish byte-identical to a run that never resized, the request
    completes "ok", and the engine reports the new shape — at pipeline
    depths 0 and 2."""
    base, _, _ = _resize_scenario(monkeypatch, depth, resize=False)
    got, eng, hold = _resize_scenario(monkeypatch, depth)
    assert hold.outcome == "ok", hold.error
    assert [f.finish_reason for _, f in got] == ["length", "length"]
    assert got == base, "streams diverged across the live resize"
    assert eng._mesh_shape_str() == "tp2xdp1"
    stats = eng.last_resize_stats
    assert stats and stats["from"] == "tp1xdp1" and stats["to"] == "tp2xdp1"
    assert stats["seconds"] > 0
    assert eng.metrics.engine_resizes_total.get(
        mode="resize", outcome="ok") == 1
    assert sum(eng.metrics.requests_quarantined_total._values.values()) == 0
    assert eng.state == "serving"


@pytest.mark.parametrize("depth", [0, 2])
@pytest.mark.parametrize("seam,expect_shape", [
    (1, "tp1xdp1"),   # drain seam: fault before the reshard -> old shape
    (2, "tp1xdp1"),   # reshard seam: plan ran, commit didn't -> old shape
    (3, "tp2xdp1"),   # resume seam: commit landed -> recover at NEW shape
], ids=["drain", "reshard", "resume"])
def test_resize_seam_fault_recovers_streams_byte_identical(
        monkeypatch, depth, seam, expect_shape):
    """A fault injected at each resize seam (drain / reshard / resume):
    the resize request reports "error", recovery lands at the expected
    shape (old for the first two seams, new for the last), and EVERY
    stream still finishes byte-identical to the never-resized run —
    nobody is quarantined (the resize serves no specific request)."""
    base, _, _ = _resize_scenario(monkeypatch, depth, resize=False)
    got, eng, hold = _resize_scenario(
        monkeypatch, depth, inject=f"resize:{seam}:runtime")
    assert hold.outcome == "error"
    assert [f.finish_reason for _, f in got] == ["length", "length"]
    assert got == base, "streams diverged after the resize-seam fault"
    assert eng._mesh_shape_str() == expect_shape
    assert eng.metrics.engine_faults_total.get(
        phase="resize", kind="injected") == 1
    assert sum(eng.metrics.requests_quarantined_total._values.values()) == 0
    assert eng.state == "serving"


def test_resize_seam_fault_zero_retries_quarantines_nobody(monkeypatch):
    """Even with a ZERO retry budget a resize-seam fault quarantines
    NOBODY: the drained streams were preserved (swapped or re-queued)
    before the seam fired, so the culprit set is empty and every stream
    replays to a byte-identical finish."""
    base, _, _ = _resize_scenario(monkeypatch, 0, resize=False)
    got, eng, hold = _resize_scenario(monkeypatch, 0,
                                      inject="resize:2:runtime", retries=0)
    assert hold.outcome == "error"
    assert [f.finish_reason for _, f in got] == ["length", "length"]
    assert got == base
    assert sum(eng.metrics.requests_quarantined_total._values.values()) == 0
    assert eng.state == "serving"


@pytest.mark.slow
def test_randomized_resize_sweep(monkeypatch):
    """Randomized resize chaos: each round posts a mid-stream resize
    with a fault at a random seam, optionally stacked with a decode
    fault.  Per-stream integrity must hold every round — each stream
    either matches the never-resized run exactly or fails alone with an
    engine_fault error — and the engine always returns to "serving" at
    a coherent shape."""
    base, _, _ = _resize_scenario(monkeypatch, 0, resize=False)
    base_by_rid = {fin.request_id: (ids, fin.finish_reason)
                   for ids, fin in base}
    rng = random.Random(4321)
    for round_i in range(5):
        specs = [f"resize:{rng.randint(1, 3)}:runtime"]
        if rng.random() < 0.5:
            specs.append(f"decode:{rng.randint(1, 4)}:runtime")
        spec = ",".join(specs)
        got, eng, hold = _resize_scenario(monkeypatch, 0, inject=spec)
        for ids, fin in got:
            if fin.finish_reason == "error":
                assert fin.error.startswith("engine_fault"), \
                    f"round {round_i} ({spec}): unexpected error {fin.error}"
                continue
            assert (ids, fin.finish_reason) == base_by_rid[fin.request_id], \
                f"round {round_i} ({spec}): stream integrity violated"
        assert hold.outcome in ("ok", "error"), f"round {round_i} ({spec})"
        assert eng.state == "serving", f"round {round_i} ({spec})"
        assert eng._mesh_shape_str() in ("tp1xdp1", "tp2xdp1"), \
            f"round {round_i} ({spec}): incoherent shape"
