"""Lane-padded KV caches (head_dim < 128 models on the Pallas path).

The stored head dim pads up to the 128-lane tile (transformer.
cache_head_dim); q is prescaled so the effective attention scale stays
1/sqrt(head_dim), and outputs slice the padded columns off — every padded
path must match its unpadded oracle EXACTLY (float tolerance)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from arks_tpu.models import get_config
from arks_tpu.models import transformer as tf


@pytest.fixture(scope="module")
def setup():
    cfg = get_config("tiny")  # head_dim 8 -> pads to 128
    params = tf.init_params(cfg, jax.random.PRNGKey(0), jnp.float32)
    return cfg, params


def _seed_caches(cfg, plain, padded, lengths, key):
    for slot in range(len(lengths)):
        plen = int(lengths[slot])
        pk = jax.random.normal(jax.random.fold_in(key, slot),
                               (cfg.num_layers, 1, plen, cfg.num_kv_heads,
                                cfg.head_dim), jnp.float32)
        pv = pk * 0.5 + 1.0
        plain = tf.insert(plain, pk, pv, jnp.asarray(slot))
        padded = tf.insert(padded, pk, pv, jnp.asarray(slot))
    return plain, padded


def test_cache_head_dim_padding_rule():
    cfg = get_config("tiny")
    assert tf.cache_head_dim(cfg, pad_head=False) == cfg.head_dim
    assert tf.cache_head_dim(cfg, pad_head=True) == 128
    big = get_config("qwen2.5-7b")
    assert tf.cache_head_dim(big, pad_head=True) == big.head_dim  # 128 already


def test_decode_step_padded_matches_plain(setup):
    cfg, params = setup
    slots = 4
    plain = tf.init_cache(cfg, slots, 64, jnp.float32)
    padded = tf.init_cache(cfg, slots, 64, jnp.float32, pad_head=True)
    assert padded.k.shape[-1] == 128
    lengths = jnp.asarray([3, 9, 17, 5], jnp.int32)
    plain, padded = _seed_caches(cfg, plain, padded, lengths,
                                 jax.random.PRNGKey(1))
    tokens = jnp.asarray([4, 5, 6, 7], jnp.int32)
    ref, plain = tf.decode_step(params, cfg, plain, tokens, lengths)
    got, padded = tf.decode_step(params, cfg, padded, tokens, lengths)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               atol=1e-4, rtol=1e-4)
    # Step 2: the padded write of step 1 reads back correctly.
    nxt = jnp.argmax(ref, axis=-1).astype(jnp.int32)
    ref2, _ = tf.decode_step(params, cfg, plain, nxt, lengths + 1)
    got2, _ = tf.decode_step(params, cfg, padded, nxt, lengths + 1)
    np.testing.assert_allclose(np.asarray(got2), np.asarray(ref2),
                               atol=1e-4, rtol=1e-4)


def test_verify_step_padded_matches_plain(setup):
    cfg, params = setup
    slots, kk = 2, 3
    plain = tf.init_cache(cfg, slots, 64, jnp.float32)
    padded = tf.init_cache(cfg, slots, 64, jnp.float32, pad_head=True)
    lengths = jnp.asarray([5, 11], jnp.int32)
    plain, padded = _seed_caches(cfg, plain, padded, lengths,
                                 jax.random.PRNGKey(2))
    tokens = jnp.asarray([[3, 4, 5], [6, 7, 8]], jnp.int32)
    ref, _ = tf.verify_step(params, cfg, plain, tokens, lengths)
    got, _ = tf.verify_step(params, cfg, padded, tokens, lengths)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               atol=1e-4, rtol=1e-4)


def test_chunk_prefill_padded_matches_one_shot(setup):
    cfg, params = setup
    prompt = list(np.random.default_rng(5).integers(2, 200, size=37))
    toks = jnp.asarray([prompt], jnp.int32)
    ref, _, _ = tf.prefill(params, cfg, toks,
                           jnp.asarray([len(prompt)], jnp.int32))

    cache = tf.init_cache(cfg, 2, 64, jnp.float32, pad_head=True)
    C = 16
    logits = None
    for start in range(0, len(prompt), C):
        chunk = prompt[start: start + C]
        padded = np.zeros((C,), np.int32)
        padded[: len(chunk)] = chunk
        logits, cache = tf.prefill_chunk(
            params, cfg, cache, jnp.asarray(0), jnp.asarray(padded),
            jnp.asarray(start, jnp.int32),
            jnp.asarray(len(chunk), jnp.int32))
    np.testing.assert_allclose(np.asarray(logits), np.asarray(ref),
                               atol=1e-3, rtol=1e-3)


def test_paged_padded_matches_plain(setup):
    cfg, params = setup
    slots, max_pages, page = 2, 4, 16
    plain = tf.init_paged_cache(cfg, slots * max_pages + 1, page,
                                jnp.float32)
    padded = tf.init_paged_cache(cfg, slots * max_pages + 1, page,
                                 jnp.float32, pad_head=True)
    assert padded.k.shape[-1] == 128
    tables = jnp.arange(slots * max_pages, dtype=jnp.int32).reshape(
        slots, max_pages)
    lengths = jnp.asarray([7, 19], jnp.int32)
    key = jax.random.PRNGKey(3)
    for slot in range(slots):
        plen = int(lengths[slot])
        n = -(-plen // page)
        pk = jax.random.normal(jax.random.fold_in(key, slot),
                               (cfg.num_layers, 1, n * page,
                                cfg.num_kv_heads, cfg.head_dim), jnp.float32)
        pv = pk * 2.0
        plain = tf.insert_pages(plain, pk, pv, tables[slot], jnp.asarray(n))
        padded = tf.insert_pages(padded, pk, pv, tables[slot], jnp.asarray(n))
    tokens = jnp.asarray([3, 4], jnp.int32)
    ref, _ = tf.decode_step(params, cfg, plain, tokens, lengths,
                            tables=tables)
    got, _ = tf.decode_step(params, cfg, padded, tokens, lengths,
                            tables=tables)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               atol=1e-4, rtol=1e-4)
