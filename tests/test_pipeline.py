"""Pipeline parallelism vs the unsharded oracle on the virtual CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from arks_tpu.models import get_config
from arks_tpu.models import transformer as tf
from arks_tpu.parallel.mesh import make_mesh
from arks_tpu.parallel import pipeline as pp
from arks_tpu.train import sft


@pytest.mark.parametrize("stages,m", [(2, 2), (2, 4)])
def test_pipeline_forward_matches_dense(stages, m):
    cfg = get_config("tiny")  # 2 layers → 1 per stage at S=2
    params = tf.init_params(cfg, jax.random.PRNGKey(0), jnp.float32)
    b, t = 4, 16
    tokens = jax.random.randint(jax.random.PRNGKey(1), (b, t), 0, cfg.vocab_size)

    # Oracle: plain stacked-scan forward (pre-final-norm hidden states).
    positions = jnp.broadcast_to(jnp.arange(t, dtype=jnp.int32), (b, t))
    h = jnp.take(params["embed"], tokens, axis=0)

    def body(h, lp):
        h, _, _ = tf.prefill_layer(h, lp, cfg, positions, None)
        return h, None
    ref, _ = jax.lax.scan(body, h, params["layers"])

    mesh = make_mesh(tensor_parallel=1, pipeline_parallel=stages,
                     devices=jax.devices()[:stages])
    params_pp = pp.shard_params_pp(params, mesh)
    got = pp.pipeline_forward(params_pp, cfg, tokens, mesh, num_microbatches=m)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_pipeline_train_step_matches_dense():
    cfg = get_config("tiny")
    optimizer = optax.adamw(1e-3)
    b, t = 4, 16
    key = jax.random.PRNGKey(2)
    tokens = jax.random.randint(key, (b, t), 0, cfg.vocab_size)
    targets = jnp.roll(tokens, -1, axis=1)
    mask = jnp.ones((b, t), jnp.float32)

    state_ref = sft.train_init(cfg, jax.random.PRNGKey(0), optimizer)
    step_ref = sft.make_train_step(cfg, optimizer)
    state_ref, loss_ref = step_ref(state_ref, tokens, targets, mask)

    mesh = make_mesh(tensor_parallel=1, pipeline_parallel=2,
                     devices=jax.devices()[:2])
    state_pp = pp.pp_train_init(cfg, jax.random.PRNGKey(0), optimizer, mesh)
    step_pp = pp.make_pp_train_step(cfg, optimizer, mesh, num_microbatches=2)
    state_pp, loss_pp = step_pp(state_pp, tokens, targets, mask)

    np.testing.assert_allclose(float(loss_pp), float(loss_ref), rtol=1e-5)
    for a, b_ in zip(jax.tree.leaves(state_pp.params),
                     jax.tree.leaves(state_ref.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   rtol=5e-5, atol=5e-5)


def test_pipeline_rejects_indivisible():
    cfg = get_config("tiny")  # 2 layers
    mesh = make_mesh(tensor_parallel=1, pipeline_parallel=4,
                     devices=jax.devices()[:4])
    params = tf.init_params(cfg, jax.random.PRNGKey(0), jnp.float32)
    with pytest.raises(ValueError, match="stages"):
        pp.pipeline_forward(params, cfg, jnp.zeros((4, 8), jnp.int32), mesh, 2)


def test_serving_engine_with_pipeline_parallelism():
    """Serving PP end to end: an engine with pipeline_parallel=2 shards
    layers AND their KV over the stage mesh axis, pipelines decode
    microbatches, and produces the same greedy tokens as the single-device
    engine — including the one-shot prefill -> insert -> decode path."""
    from arks_tpu.engine import (
        EngineConfig, InferenceEngine, Request, SamplingParams)
    from arks_tpu.engine.tokenizer import ByteTokenizer
    from arks_tpu.models import get_config

    cfg = get_config("tiny")
    prompts = [[int(x) % cfg.vocab_size for x in range(5, 29)],   # 24 tokens
               [int(x) % cfg.vocab_size for x in range(40, 50)]]  # 10 tokens

    def run(pp):
        ecfg = EngineConfig(model="tiny", num_slots=4, max_cache_len=64,
                            prefill_buckets=(16, 32), steps_per_dispatch=4,
                            pipeline_parallel=pp, prefix_cache_mb=0)
        eng = InferenceEngine(cfg, ecfg, ByteTokenizer())
        if pp > 1:
            # Chunked prefill + prefix cache off; cache stage-sharded.
            assert eng._chunk == 0 and eng._prefix is None
        reqs = [Request(f"p{i}", p, SamplingParams(max_tokens=5,
                                                   temperature=0.0,
                                                   ignore_eos=True))
                for i, p in enumerate(prompts)]
        for r in reqs:
            eng.add_request(r)
        for _ in range(100):
            eng.step(block_s=0.01)
            if eng.num_running == 0 and eng._queue.empty():
                break
        outs = []
        for r in reqs:
            ids = []
            while True:
                out = r.outputs.get(timeout=60)
                ids.extend(out.token_ids)
                if out.finished:
                    break
            outs.append(ids)
        return outs

    assert run(2) == run(1)


def test_serving_engine_pp_paged():
    """The paged layout composes with pipeline parallelism: the pool
    shards over 'stage' on its layer dim, admissions insert through the
    block tables, decode pipelines microbatches against table-mapped
    pages (pp_decode_step_paged), and greedy output matches the pp=1 slot
    oracle.  Slot reuse is exercised too: more prompts than slots forces
    page free/realloc between requests."""
    from arks_tpu.engine import (
        EngineConfig, InferenceEngine, Request, SamplingParams)
    from arks_tpu.engine.tokenizer import ByteTokenizer
    from arks_tpu.models import get_config

    cfg = get_config("tiny")
    prompts = [[int(x) % cfg.vocab_size for x in range(5, 29)],
               [int(x) % cfg.vocab_size for x in range(40, 50)],
               [3] * 17,
               [int(x) % cfg.vocab_size for x in range(7, 38)],
               [9, 8, 7, 6, 5],
               [int(x) % cfg.vocab_size for x in range(11, 43)]]

    def run(pp, layout):
        ecfg = EngineConfig(model="tiny", num_slots=2, max_cache_len=64,
                            prefill_buckets=(16, 32), steps_per_dispatch=4,
                            pipeline_parallel=pp, prefix_cache_mb=0,
                            kv_layout=layout)
        eng = InferenceEngine(cfg, ecfg, ByteTokenizer())
        eng.start()
        outs = []
        try:
            reqs = [Request(f"p{i}", list(p), SamplingParams(
                max_tokens=6, temperature=0.0, ignore_eos=True))
                for i, p in enumerate(prompts)]
            for r in reqs:
                eng.add_request(r)
            for r in reqs:
                ids = []
                while True:
                    out = r.outputs.get(timeout=120)
                    ids.extend(out.token_ids)
                    if out.finished:
                        break
                outs.append(ids)
        finally:
            eng.stop()
        return outs

    assert run(2, "paged") == run(1, "slot")
