"""Model-layer correctness: prefill/decode agreement, GQA, TP equivalence.

These are the engine-level tests the reference lacks entirely (SURVEY.md §4:
its controller tests assert nothing about behavior); a CPU-backed JAX rig
makes serving testable without TPUs.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from arks_tpu.models import get_config
from arks_tpu.models import transformer as tf
from arks_tpu.parallel.mesh import make_mesh


def _full_logits(params, cfg, token_ids, mesh=None):
    """Reference path: prefill over each prefix → logits after each token."""
    n = len(token_ids)
    outs = []
    for i in range(1, n + 1):
        toks = jnp.asarray([token_ids[:i]], dtype=jnp.int32)
        logits, _, _ = tf.prefill(params, cfg, toks, jnp.asarray([i], jnp.int32), mesh)
        outs.append(np.asarray(logits[0]))
    return np.stack(outs)


@pytest.mark.parametrize("name", ["tiny", "tiny-gqa"])
def test_decode_matches_prefill(name):
    cfg = get_config(name)
    key = jax.random.PRNGKey(0)
    params = tf.init_params(cfg, key, jnp.float32)
    ids = list(jax.random.randint(jax.random.PRNGKey(1), (10,), 0, cfg.vocab_size))
    ids = [int(x) for x in ids]

    ref = _full_logits(params, cfg, ids)

    # Prefill the first 4 tokens, insert into slot 2 of a 4-slot cache,
    # then decode the rest one token at a time.
    n_prefill = 4
    cache = tf.init_cache(cfg, num_slots=4, max_len=32, dtype=jnp.float32)
    toks = jnp.asarray([ids[:n_prefill]], jnp.int32)
    logits, ks, vs = tf.prefill(params, cfg, toks, jnp.asarray([n_prefill], jnp.int32))
    np.testing.assert_allclose(np.asarray(logits[0]), ref[n_prefill - 1], rtol=2e-4, atol=2e-4)

    slot = 2
    cache = tf.insert(cache, ks, vs, jnp.asarray(slot))
    lengths = jnp.zeros((4,), jnp.int32).at[slot].set(n_prefill)
    tokens = jnp.zeros((4,), jnp.int32)

    for i in range(n_prefill, len(ids)):
        tokens = tokens.at[slot].set(ids[i])
        logits, cache = tf.decode_step(params, cfg, cache, tokens, lengths)
        np.testing.assert_allclose(np.asarray(logits[slot]), ref[i], rtol=2e-4, atol=2e-4)
        lengths = lengths.at[slot].set(i + 1)


@pytest.mark.parametrize("tp,dp", [(8, 1), (4, 2), (2, 4)])
def test_tensor_parallel_equivalence(tp, dp):
    """Sharded decode over a (dp, tp) mesh must match the single-device path."""
    cfg = get_config("tiny-gqa")  # 4 kv heads: exercises both sharded (tp<=4) and replicated kv
    params = tf.init_params(cfg, jax.random.PRNGKey(0), jnp.float32)
    b, prefill_len = 8, 6
    ids = np.asarray(jax.random.randint(jax.random.PRNGKey(7), (b, prefill_len + 3), 0, cfg.vocab_size))

    def run(mesh, batch_axis):
        cache = tf.init_cache(cfg, num_slots=b, max_len=16, dtype=jnp.float32)
        if mesh is not None:
            params_s = tf.shard_params(params, cfg, mesh)
        else:
            params_s = params
        for s in range(b):
            toks = jnp.asarray(ids[s : s + 1, :prefill_len], jnp.int32)
            _, ks, vs = tf.prefill(params_s, cfg, toks, jnp.asarray([prefill_len], jnp.int32), mesh)
            cache = tf.insert(cache, ks, vs, jnp.asarray(s))
        lengths = jnp.full((b,), prefill_len, jnp.int32)
        outs = []
        for t in range(3):
            tokens = jnp.asarray(ids[:, prefill_len + t], jnp.int32)
            logits, cache = tf.decode_step(params_s, cfg, cache, tokens, lengths,
                                           mesh, batch_axis)
            outs.append(np.asarray(logits))
            lengths = lengths + 1
        return np.stack(outs)

    ref = run(None, None)
    mesh = make_mesh(tensor_parallel=tp, data_parallel=dp)
    got = run(mesh, "data" if dp > 1 else None)
    np.testing.assert_allclose(got, ref, rtol=5e-4, atol=5e-4)


def test_param_count_matches_formula():
    cfg = get_config("qwen2.5-0.5b")
    # Known ballpark: ~0.49B params (with tied embeddings).
    assert 0.4e9 < cfg.num_params() < 0.65e9


def test_hf_config_roundtrip():
    from arks_tpu.models.config import ModelConfig
    d = {
        "architectures": ["Qwen2ForCausalLM"], "vocab_size": 1000,
        "hidden_size": 64, "intermediate_size": 128, "num_hidden_layers": 2,
        "num_attention_heads": 8, "num_key_value_heads": 4,
        "rope_theta": 1e6, "rms_norm_eps": 1e-6, "tie_word_embeddings": True,
        "eos_token_id": 5, "max_position_embeddings": 2048,
    }
    cfg = ModelConfig.from_hf_config(d)
    assert cfg.qkv_bias and cfg.num_kv_heads == 4 and cfg.head_dim == 8
    assert cfg.eos_token_ids == (5,)
