"""End-to-end request tracing: W3C context propagation, the per-thread
event rings and off-thread assembly, tail-based retention, the flight
recorder, and the two serving gates —

- **byte identity**: the same workload with tracing on and with
  ``ARKS_TRACE=0`` must emit byte-identical token streams (the tracer
  records, it never schedules) at pipeline depths 0 and 2 for plain,
  guided, and speculative traffic;
- **correlation**: a gateway-originated request's exported trace carries
  spans from all three components (gateway admit, router pick, engine
  lifecycle) under the ONE trace id minted at the gateway, including a
  park/unpark pair and the pipelined issue->resolve spans.
"""

import json
import time
import urllib.error
import urllib.request

import pytest

from arks_tpu.engine import EngineConfig, InferenceEngine, Request, SamplingParams
from arks_tpu.engine.tokenizer import ByteTokenizer
from arks_tpu.models import get_config
from arks_tpu.obs import trace as trace_mod
from arks_tpu.obs.trace import TraceCtx, Tracer, TraceStore


# ------------------------------------------------------------ W3C context

def test_traceparent_roundtrip():
    ctx = TraceCtx()
    hdr = ctx.traceparent()
    assert hdr == f"00-{ctx.trace_id}-{ctx.span_id}-01"
    parsed = TraceCtx.parse(hdr)
    assert parsed.trace_id == ctx.trace_id
    assert parsed.parent_id == ctx.span_id
    assert parsed.span_id != ctx.span_id  # a new span id for the next hop


@pytest.mark.parametrize("bad", [
    None, "", "garbage", "00-abc-def-01",
    "00-" + "g" * 32 + "-" + "1" * 16 + "-01",      # non-hex trace id
    "00-" + "0" * 32 + "-" + "1" * 16 + "-01",      # all-zero trace id
    "00-" + "1" * 32 + "-" + "0" * 16 + "-01",      # all-zero span id
    "00-" + "1" * 31 + "-" + "1" * 16 + "-01",      # wrong length
])
def test_traceparent_rejects_malformed(bad):
    assert TraceCtx.parse(bad) is None


def test_child_keeps_trace_id_and_links_parent():
    root = TraceCtx()
    child = root.child()
    assert child.trace_id == root.trace_id
    assert child.parent_id == root.span_id
    assert child.span_id != root.span_id


def test_from_headers_folds_upstream_spans():
    root = TraceCtx()
    spans = [{"component": "gateway", "name": "gateway.admit",
              "start": 1.0, "end": 2.0}]
    headers = {trace_mod.TRACEPARENT_HEADER: root.traceparent(),
               trace_mod.SPANS_HEADER: trace_mod.spans_header(spans)}
    ctx = TraceCtx.from_headers(headers)
    assert ctx.trace_id == root.trace_id
    assert ctx.upstream == spans
    # Absent/garbage headers -> a fresh root, never an exception.
    fresh = TraceCtx.from_headers({trace_mod.SPANS_HEADER: "not json"})
    assert fresh.trace_id != root.trace_id and fresh.upstream == []


# ----------------------------------------------------- tracer unit tests

def _mk_tracer(monkeypatch, **env):
    for k, v in env.items():
        monkeypatch.setenv(k, str(v))
    return Tracer()  # collector thread NOT started: flush() driven by hand


def test_tracer_assembles_paired_spans(monkeypatch):
    tr = _mk_tracer(monkeypatch, ARKS_TRACE="1", ARKS_TRACE_SAMPLE="1.0")
    tr.register("r1", ctx=None, tier="gold")
    tr.evt("r1", "queue", "B")
    tr.evt("r1", "queue", "E")
    tr.evt("r1", "prefill", "B", 7)
    tr.evt("r1", "prefill", "E")
    tr.evt("r1", "first_token", "I", 0.01)
    tr.evt("r1", "finish", "I", "length")
    tr.flush()
    t = tr.store.get("r1")
    assert t is not None and t["tier"] == "gold" and t["flags"] == []
    by_name = {s["name"]: s for s in t["spans"]}
    assert by_name["queue"]["end"] >= by_name["queue"]["start"]
    assert by_name["prefill"]["arg"] == 7
    assert by_name["finish"]["arg"] == "length"
    assert t["end"] >= t["start"]


def test_tail_retention_keeps_flagged_traces_only(monkeypatch):
    tr = _mk_tracer(monkeypatch, ARKS_TRACE="1", ARKS_TRACE_SAMPLE="0.0")
    tr.evt("ok", "queue", "B")
    tr.evt("ok", "finish", "I", "length")
    tr.evt("bad", "queue", "B")
    tr.evt("bad", "fault", "I", "decode/runtime")
    tr.evt("bad", "finish", "I", "length")
    tr.flush()
    assert tr.store.get("ok") is None          # sampled out
    t = tr.store.get("bad")
    assert t is not None and t["flags"] == ["faulted"]


def test_slo_violation_flags_trace(monkeypatch):
    tr = _mk_tracer(monkeypatch, ARKS_TRACE="1", ARKS_TRACE_SAMPLE="0.0")
    tr.evt("s", "slo_violation", "I", (120.0, 100.0))
    tr.evt("s", "finish", "I", "stop")
    tr.flush()
    assert tr.store.get("s")["flags"] == ["slo_violation"]


def test_store_evicts_oldest_unflagged_first():
    store = TraceStore(cap=2)

    def t(rid, flags):
        return {"trace_id": rid + "-tid", "request_id": rid,
                "flags": flags, "spans": [], "start": 0, "end": 1}
    store.add(t("a", ["faulted"]))
    store.add(t("b", []))
    store.add(t("c", []))
    assert store.get("a") is not None, "flagged trace evicted before bulk"
    assert store.get("b") is None
    assert store.get("c") is not None


def test_flight_recorder_tail_orders_across_threads(monkeypatch):
    import threading

    tr = _mk_tracer(monkeypatch, ARKS_TRACE="1")
    tr.evt("x", "queue", "B")
    th = threading.Thread(target=lambda: tr.evt("", "spill", "I", 3))
    th.start()
    th.join()
    tr.evt("x", "finish", "I", "stop")
    tail = tr.tail(10)
    assert [r["name"] for r in tail] == ["queue", "spill", "finish"]
    assert len({r["thread"] for r in tail}) == 2


def test_disabled_tracer_is_inert(monkeypatch):
    tr = _mk_tracer(monkeypatch, ARKS_TRACE="0")
    tr.evt("r", "queue", "B")
    tr.evt("r", "finish", "I", "stop")
    tr.flush()
    tr.register("r")
    assert tr.tail() == [] and tr.store.get("r") is None


def test_pending_gc_bounds_terminal_less_timelines(monkeypatch):
    tr = _mk_tracer(monkeypatch, ARKS_TRACE="1")
    tr._PENDING_CAP = 4
    for i in range(8):  # aborted requests: no terminal event, ever
        tr.evt(f"zombie-{i}", "queue", "B")
    tr.flush()
    assert len(tr._pending) == 4


# -------------------------------------------------- engine-level fixtures

def _mk_engine(monkeypatch, *, depth=0, trace="1", spec=False, **kw):
    monkeypatch.setenv("ARKS_TRACE", trace)
    monkeypatch.setenv("ARKS_PIPELINE_DEPTH", str(depth))
    monkeypatch.setenv("ARKS_MIXED_STEP", "auto")
    cfg = get_config("tiny")
    defaults = dict(model="tiny", num_slots=2, max_cache_len=64,
                    prefill_buckets=(8, 16, 32), steps_per_dispatch=4,
                    prefill_chunk=16, kv_layout="paged")
    if spec:
        defaults.update(draft_model="tiny", draft_len=3)
    defaults.update(kw)
    eng = InferenceEngine(cfg, EngineConfig(**defaults), ByteTokenizer())
    if depth:
        assert eng._pipe_warm_wait(300) == "ready"
    return cfg, eng


def _drive(eng, n_steps=2000):
    for _ in range(n_steps):
        try:
            eng.step(block_s=0.01)
        except Exception as e:  # noqa: BLE001 — routed like _run_loop
            eng._recover_from_fault(e)
        if (eng.num_running == 0 and eng._queue.empty()
                and not eng._prefilling and eng.state == "serving"):
            break


def _collect(req):
    ids, fin = [], None
    while True:
        out = req.outputs.get(timeout=120)
        ids.extend(out.token_ids)
        if out.finished:
            fin = out
            break
    return ids, fin.finish_reason


def _run_workload(eng, cfg, guided=False):
    reqs = [
        Request("g0", [5, 6, 7], SamplingParams(
            max_tokens=5, temperature=0.0, ignore_eos=True)),
        Request("s0", [int(x) % cfg.vocab_size for x in range(3, 40)],
                SamplingParams(max_tokens=5, temperature=0.8, top_p=0.9,
                               seed=7, ignore_eos=True)),
    ]
    if guided:
        reqs.append(Request("j0", [4, 8, 2], SamplingParams(
            max_tokens=6, temperature=0.0, guide=("json", ""))))
    for r in reqs:
        eng.add_request(r)
    _drive(eng)
    return [_collect(r) for r in reqs]


# -------------------------------------------------- byte-identity gates

@pytest.mark.parametrize("depth", [0, 2])
def test_stream_identity_tracing_on_vs_off(monkeypatch, depth):
    """Plain + guided traffic: token streams with the tracer recording
    are byte-identical to ARKS_TRACE=0 at this pipeline depth."""
    outs = {}
    for trace in ("1", "0"):
        cfg, eng = _mk_engine(monkeypatch, depth=depth, trace=trace)
        assert eng.trace.enabled == (trace == "1")
        outs[trace] = _run_workload(eng, cfg, guided=True)
        if trace == "1":
            eng.trace.flush()
            # The traced run really recorded: finished timelines landed.
            assert eng.trace.store.get("g0") is not None
            if depth:
                spans = eng.trace.store.get("g0")["spans"]
                assert any(s["name"] == "pipe" for s in spans)
    assert outs["1"] == outs["0"]


@pytest.mark.parametrize("depth", [0, 2])
def test_stream_identity_spec_traffic(monkeypatch, depth):
    """Speculative traffic (draft+verify in the mixed dispatch): accepted
    streams are identical with tracing on and off at this depth."""
    outs = {}
    for trace in ("1", "0"):
        cfg, eng = _mk_engine(monkeypatch, depth=depth, trace=trace,
                              spec=True)
        outs[trace] = _run_workload(eng, cfg)
    assert outs["1"] == outs["0"]


# ------------------------------------------------- chaos / flight recorder

def test_fault_trace_retained_with_replay_and_flight_tail(monkeypatch):
    """A chaos-injected decode fault must leave a RETAINED trace (despite
    a 0.0 sample rate — tail-based retention) carrying the recovery and
    replay spans plus the flight-recorder tail."""
    monkeypatch.setenv("ARKS_TRACE_SAMPLE", "0.0")
    cfg, eng = _mk_engine(monkeypatch, depth=0)
    # Third decode dispatch: survivors hold generated tokens by then, so
    # recovery takes the token-REPLAY path (not a cold re-admit).
    eng._faults.arm("decode:3:runtime")
    outs = _run_workload(eng, cfg)
    assert [fin for _, fin in outs] == ["length", "length"]
    eng.trace.flush()
    flagged = [t for t in eng.trace.store.all() if "faulted" in t["flags"]]
    assert flagged, "fault-flagged trace was not retained"
    t = flagged[0]
    names = [s["name"] for s in t["spans"]]
    assert "replay" in names
    assert "recover" in names            # engine-scope recovery span attached
    assert t["flight_tail"], "flight-recorder tail not attached"
    # The tail is the PRE-fault timeline: the scheduler-phase events that
    # led up to the dispatch that blew, ending at the recovery entry.
    assert any(r["name"].startswith("phase.") for r in t["flight_tail"])
    assert {"t", "rid", "name", "ph", "thread"} <= set(t["flight_tail"][-1])


# ------------------------------------- three-component correlation (e2e)

def test_gateway_router_engine_one_trace(monkeypatch):
    """A request through gateway -> router -> engine server exports ONE
    trace: the id minted at the gateway, the gateway admit + router pick
    spans, a park/unpark pair (guide compile), and the pipelined
    issue->resolve spans — plus the Perfetto export of the same."""
    from arks_tpu.control import resources as res
    from arks_tpu.control.store import Store
    from arks_tpu.engine import guides as guides_mod
    from arks_tpu.gateway.server import Gateway
    from arks_tpu.router import Discovery, Router
    from arks_tpu.server import OpenAIServer

    monkeypatch.setenv("ARKS_TRACE", "1")
    monkeypatch.setenv("ARKS_TRACE_SAMPLE", "1.0")
    monkeypatch.setenv("ARKS_PIPELINE_DEPTH", "2")
    monkeypatch.setenv("ARKS_MIXED_STEP", "auto")

    # Make the cold guide compile span several scheduler passes so the
    # guided request deterministically parks (park.guide B ... E).
    orig_build = guides_mod.GuideCompiler._build

    def slow_build(self, rx):
        time.sleep(0.5)
        return orig_build(self, rx)
    monkeypatch.setattr(guides_mod.GuideCompiler, "_build", slow_build)

    cfg = get_config("tiny")
    engine = InferenceEngine(cfg, EngineConfig(
        model="tiny", num_slots=2, max_cache_len=64,
        prefill_buckets=(8, 16, 32), steps_per_dispatch=4,
        prefill_chunk=16, kv_layout="paged"), ByteTokenizer())
    assert engine._pipe_warm_wait(300) == "ready"
    engine.start()
    srv = OpenAIServer(engine, served_model_name="m1",
                       host="127.0.0.1", port=0)
    srv.start(background=True)

    monkeypatch.setenv("ARKS_DECODE_ADDRS", f"127.0.0.1:{srv.port}")
    monkeypatch.delenv("ARKS_PREFILL_ADDRS", raising=False)
    router = Router(Discovery(None), "m1", host="127.0.0.1", port=0,
                    policy="round_robin", unified=True)
    router.start(background=True)

    store = Store()
    store.create(res.Endpoint(name="m1", namespace="team-a", spec={},
                              status={"routes": [{"backend": {
                                  "addresses": [f"127.0.0.1:{router.port}"]},
                                  "weight": 1}]}))
    store.create(res.Token(name="alice", namespace="team-a", spec={
        "token": "sk-alice", "qos": [{"endpoint": {"name": "m1"}}]}))
    gw = Gateway(store, host="127.0.0.1", port=0, quota_sync_s=0.2)
    gw.start(background=True)
    deadline = time.monotonic() + 10
    while not gw.qos.token_known("sk-alice") and time.monotonic() < deadline:
        time.sleep(0.02)

    try:
        req = urllib.request.Request(
            f"http://127.0.0.1:{gw.port}/v1/completions",
            data=json.dumps({
                "model": "m1", "prompt": "hello", "max_tokens": 5,
                "temperature": 0, "ignore_eos": True,
                "response_format": {"type": "json_object"},
            }).encode(),
            headers={"Content-Type": "application/json",
                     "Authorization": "Bearer sk-alice"})
        with urllib.request.urlopen(req, timeout=120) as r:
            assert json.load(r)["usage"]["completion_tokens"] >= 1

        def _get(path):
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{srv.port}{path}", timeout=30) as r:
                return json.load(r)

        # Find the gateway-originated trace among retained timelines.
        trace = None
        for _ in range(50):
            for entry in _get("/v1/traces")["traces"]:
                t = _get(f"/v1/traces/{entry['trace_id']}")
                if any(s.get("component") == "gateway" for s in t["spans"]):
                    trace = t
                    break
            if trace:
                break
            time.sleep(0.1)
        assert trace, "no gateway-correlated trace retained"

        comps = {s.get("component") for s in trace["spans"]}
        assert {"gateway", "router", "engine"} <= comps
        by_name = {}
        for s in trace["spans"]:
            by_name.setdefault(s["name"], []).append(s)
        assert "gateway.admit" in by_name and "router.pick" in by_name
        # One trace id end to end: the engine kept the gateway's root id
        # (64-bit-hex trace id from the traceparent the gateway minted).
        assert len(trace["trace_id"]) == 32
        # A park/unpark pair: the guided request parked on its compile.
        park = by_name["park.guide"][0]
        assert park["end"] is not None and park["end"] > park["start"]
        # Pipelined issue->resolve spans overlap the request's lifetime.
        pipe = by_name.get("pipe", [])
        assert pipe and all(p["end"] >= p["start"] for p in pipe)

        # The Perfetto export carries the same correlated timeline.
        export = _get("/v1/traces/export")
        names = {e["name"] for e in export["traceEvents"]}
        assert {"gateway.admit", "router.pick"} <= names
        pids = {e["pid"] for e in export["traceEvents"]}
        assert len(pids) >= 2  # gateway/router/engine rows are distinct
    finally:
        gw.stop()
        router.stop()
        srv.stop()
        engine.stop()


def test_trace_endpoint_404_when_unknown(monkeypatch):
    from arks_tpu.server import OpenAIServer

    monkeypatch.setenv("ARKS_TRACE", "1")
    cfg, eng = _mk_engine(monkeypatch, depth=0)
    eng.start()
    srv = OpenAIServer(eng, served_model_name="m1",
                       host="127.0.0.1", port=0)
    srv.start(background=True)
    try:
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/v1/traces/nope", timeout=30)
        assert ei.value.code == 404
    finally:
        srv.stop()
        eng.stop()


# ------------------------------------------------------ profiler windows

def test_profiler_window_start_stop(monkeypatch, tmp_path):
    from arks_tpu.obs import profiler as prof_mod

    monkeypatch.setenv("ARKS_PROF_DIR", str(tmp_path / "prof"))
    pw = prof_mod.ProfilerWindows()
    out = pw.start()
    assert out["ok"] and out["dir"].startswith(str(tmp_path))
    assert pw.start() == {"ok": False, "error": "already_active",
                          "dir": out["dir"]}
    stopped = pw.stop()
    assert stopped["ok"] and stopped["dir"] == out["dir"]
    assert pw.stop() == {"ok": False, "error": "not_active"}


def test_profiler_auto_arm_threshold(monkeypatch, tmp_path):
    from arks_tpu.obs import profiler as prof_mod

    monkeypatch.setenv("ARKS_PROF_DIR", str(tmp_path / "prof"))
    monkeypatch.setenv("ARKS_PROF_AUTO_ARM", "4.0")
    monkeypatch.setenv("ARKS_PROF_WINDOW_S", "0.05")
    pw = prof_mod.ProfilerWindows()
    for _ in range(40):
        pw.on_step(0.01)         # steady trailing median
    assert not pw.active
    pw.on_step(0.2)              # 20x the median: arm a window
    assert pw.active
    time.sleep(0.1)
    pw.on_step(0.01)             # window elapsed: closes itself
    assert not pw.active
