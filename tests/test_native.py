"""Native C++ gateway data-plane library vs the pure-Python oracles.

The build environment has g++; the library compiles on demand.  Every test
asserts native availability explicitly — a silent fallback to Python would
make this suite vacuous.
"""

import json

import pytest

from arks_tpu.gateway import native
from arks_tpu.gateway.ratelimiter import MemoryCounterBackend, RateLimiter
from arks_tpu.gateway.server import PyUsageScanner, make_usage_scanner

pytestmark = pytest.mark.skipif(
    not native.available(), reason="native lib unavailable (no g++?)")


def test_native_lib_builds():
    assert native.available()


# ---------------------------------------------------------------------------
# Counter store
# ---------------------------------------------------------------------------


def test_counter_basic_semantics():
    b = native.NativeCounterBackend()
    assert b.get("k") == 0
    assert b.incr("k", 3, ttl_s=60) == 3
    assert b.incr("k", 2, ttl_s=60) == 5
    assert b.get("k") == 5
    assert b.get("other") == 0


def test_counter_expiry():
    b = native.NativeCounterBackend()
    b.incr("e", 7, ttl_s=0)  # expires immediately
    assert b.get("e") == 0
    assert b.incr("e", 1, ttl_s=60) == 1  # window restarted, not 8


def test_counter_parity_with_python_backend():
    nat, py = native.NativeCounterBackend(), MemoryCounterBackend()
    ops = [("a", 1), ("b", 5), ("a", 2), ("c", 10), ("a", 1)]
    for key, amt in ops:
        assert nat.incr(key, amt, 60) == py.incr(key, amt, 60)
    for key in ("a", "b", "c", "missing"):
        assert nat.get(key) == py.get(key)


def test_rate_limiter_uses_native_backend_by_default():
    rl = RateLimiter()
    assert type(rl.backend).__name__ == "NativeCounterBackend"
    rl.do_limit("ns", "u", "m", {"rpm": 1})
    res = rl.check_limit("ns", "u", "m", {"rpm": 1}, {})
    assert res[0].over  # 1 used + 1 requested > limit 1


# ---------------------------------------------------------------------------
# SSE usage scanner
# ---------------------------------------------------------------------------


def _frames(usage_in_last=True):
    chunks = [
        {"id": "c1", "choices": [{"delta": {"content": "hi"}}], "usage": None},
        {"id": "c1", "choices": [{"delta": {"content": "!"}}], "usage": None},
    ]
    final = {"id": "c1", "choices": [],
             "usage": {"prompt_tokens": 11, "completion_tokens": 7,
                       "total_tokens": 18}}
    frames = [f"data: {json.dumps(c)}\n\n" for c in chunks]
    if usage_in_last:
        frames.append(f"data: {json.dumps(final)}\n\n")
    frames.append("data: [DONE]\n\n")
    return "".join(frames).encode()


def test_sse_scanner_whole_stream():
    s = native.SseUsageScanner()
    s.feed(_frames())
    assert s.usage() == {"prompt_tokens": 11, "completion_tokens": 7,
                         "total_tokens": 18}
    assert s.done


@pytest.mark.parametrize("n", [1, 2, 3, 7, 16])
def test_sse_scanner_fragmentation_parity(n):
    """Any chunking (including keys split mid-token) must match the Python
    oracle's result."""
    raw = _frames()
    pieces = [raw[i: i + n] for i in range(0, len(raw), n)]
    nat, py = native.SseUsageScanner(), PyUsageScanner()
    for p in pieces:
        nat.feed(p)
        py.feed(p)
    assert nat.usage() == py.usage() == {
        "prompt_tokens": 11, "completion_tokens": 7, "total_tokens": 18}


def test_sse_scanner_later_usage_supersedes_fully():
    """A later usage frame replaces the whole earlier dict — a missing field
    must NOT leak through from a previous frame (continuous usage stats)."""
    early = b'data: {"usage": {"prompt_tokens": 100, "completion_tokens": 1, "total_tokens": 101}}\n\n'
    final = b'data: {"usage": {"prompt_tokens": 100, "completion_tokens": 50}}\n\n'
    nat, py = native.SseUsageScanner(), PyUsageScanner()
    for s in (nat, py):
        s.feed(early)
        s.feed(final)
    assert nat.usage() == py.usage() == {"prompt_tokens": 100,
                                         "completion_tokens": 50}


@pytest.mark.parametrize("later", [
    b'data: {"usage": {}}\n\n',
    b'data: {"usage": {"foo": "bar"}}\n\n',
    b'data: {"usage": {"prompt_tokens": "NaN"}}\n\n',
    b'data: {"usage": {"prompt_tokens": true}}\n\n',
])
def test_sse_scanner_empty_usage_does_not_clear(later):
    """An empty or non-numeric usage frame after a real one must not clear
    the captured counters, in either backend (they must agree: metering
    can't depend on whether the C++ library built)."""
    early = (b'data: {"usage": {"prompt_tokens": 3, "completion_tokens": 2,'
             b' "total_tokens": 5}}\n\n')
    nat, py = native.SseUsageScanner(), PyUsageScanner()
    for s in (nat, py):
        s.feed(early)
        s.feed(later)
    assert nat.usage() == py.usage() == {
        "prompt_tokens": 3, "completion_tokens": 2, "total_tokens": 5}


@pytest.mark.parametrize("n", [1, 3, 16])
def test_sse_scanner_fragmented_empty_usage_parity(n):
    """Fragmented feeds of an empty-usage stream agree across backends."""
    raw = (b'data: {"usage": {"prompt_tokens": 9, "total_tokens": 9}}\n\n'
           b'data: {"usage": {}}\n\n'
           b'data: [DONE]\n\n')
    nat, py = native.SseUsageScanner(), PyUsageScanner()
    for i in range(0, len(raw), n):
        nat.feed(raw[i: i + n])
        py.feed(raw[i: i + n])
    assert nat.usage() == py.usage() == {"prompt_tokens": 9,
                                         "total_tokens": 9}


def test_sse_scanner_ignores_tokens_outside_usage_object():
    """Numbers after the usage object's closing brace must not be parsed."""
    s = native.SseUsageScanner()
    s.feed(b'data: {"usage": {"prompt_tokens": 4}, "total_tokens": 999}\n\n')
    assert s.usage() == {"prompt_tokens": 4}


def test_sse_scanner_crlf_and_no_usage():
    s = native.SseUsageScanner()
    s.feed(b'data: {"usage": null}\r\n\r\ndata: [DONE]\r\n\r\n')
    assert s.usage() is None
    assert s.done


def test_make_usage_scanner_prefers_native():
    assert type(make_usage_scanner()).__name__ == "SseUsageScanner"
