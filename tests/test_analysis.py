"""arkslint self-tests: rule fixtures, call-graph behavior, the
hot-path acceptance diff against the legacy hand-curated tuple, and the
CLI / baseline / generated-docs contracts.

Fixtures are in-memory ``SourceTree`` dicts — the rules see no
difference from the on-disk tree, so each invariant gets a positive AND
a negative case without touching the real engine.
"""

import json
import pathlib
import subprocess
import sys
import time

from arks_tpu.analysis import SourceTree, repo_root, run_rules
from arks_tpu.analysis.baseline import MAX_SUPPRESSIONS, Baseline
from arks_tpu.analysis.callgraph import CallGraph
from arks_tpu.analysis.rules import hotpath as hotpath_rule


# ------------------------------------------------------------ call graph

def test_callgraph_direct_and_self_edges():
    tree = SourceTree({"arks_tpu/m.py": (
        "class C:\n"
        "    def a(self):\n"
        "        self.b()\n"
        "    def b(self):\n"
        "        pass\n"
        "    def c(self):\n"
        "        pass\n"
    )})
    g = CallGraph(tree)
    root = g.find("arks_tpu/m.py", "C", "a")
    reach = g.reachable([root])
    assert g.find("arks_tpu/m.py", "C", "b") in reach
    assert g.find("arks_tpu/m.py", "C", "c") not in reach


def test_callgraph_callback_reference_counts_as_edge():
    """``on_evict = self._note`` (no call parens) must still pull the
    callback into the reachable set — the scheduler registers hot-path
    callbacks exactly this way."""
    tree = SourceTree({"arks_tpu/m.py": (
        "class C:\n"
        "    def a(self):\n"
        "        self.alloc.on_evict = self._note\n"
        "    def _note(self):\n"
        "        pass\n"
    )})
    g = CallGraph(tree)
    reach = g.reachable([g.find("arks_tpu/m.py", "C", "a")])
    assert g.find("arks_tpu/m.py", "C", "_note") in reach


def test_callgraph_cross_module_edges():
    tree = SourceTree({
        "arks_tpu/a.py": (
            "from arks_tpu.b import helper\n"
            "from arks_tpu import c\n"
            "def top():\n"
            "    helper()\n"
            "    c.other()\n"
        ),
        "arks_tpu/b.py": "def helper():\n    pass\n",
        "arks_tpu/c.py": "def other():\n    pass\n",
    })
    g = CallGraph(tree)
    reach = g.reachable([g.find("arks_tpu/a.py", None, "top")])
    assert g.find("arks_tpu/b.py", None, "helper") in reach
    assert g.find("arks_tpu/c.py", None, "other") in reach


def test_callgraph_boundary_stops_propagation():
    tree = SourceTree({"arks_tpu/m.py": (
        "class C:\n"
        "    def a(self):\n"
        "        self._resolve_x()\n"
        "    def _resolve_x(self):\n"
        "        self.deep()\n"
        "    def deep(self):\n"
        "        pass\n"
    )})
    g = CallGraph(tree)
    reach = g.reachable(
        [g.find("arks_tpu/m.py", "C", "a")],
        stop=lambda fn: fn.name.startswith("_resolve_"))
    assert g.find("arks_tpu/m.py", "C", "_resolve_x") not in reach
    assert g.find("arks_tpu/m.py", "C", "deep") not in reach


# -------------------------------------------------------- hotpath fixtures

_ENGINE_FIXTURE = {
    "arks_tpu/engine/engine.py": (
        "import time\n"
        "import numpy as np\n"
        "class InferenceEngine:\n"
        "    def step(self):\n"
        "        self._issue()\n"
        "        self._resolve_decode()\n"
        "        self.alloc.on_evict = self._cb\n"
        "    def _issue(self):\n"
        "        return np.asarray(self.buf)\n"
        "    def _cb(self):\n"
        "        time.sleep(0.1)\n"
        "    def _resolve_decode(self):\n"
        "        return np.asarray(self.out)\n"
        "    def _unreached(self):\n"
        "        return np.asarray(self.other)\n"
    ),
}


def test_hotpath_flags_reachable_fetch_not_tails_or_unreached():
    findings = run_rules(SourceTree(_ENGINE_FIXTURE), ["hotpath"])
    fetches = {f.qualname for f in findings if f.check == "blocking-fetch"}
    assert "InferenceEngine._issue" in fetches
    assert "InferenceEngine._resolve_decode" not in fetches
    assert "InferenceEngine._unreached" not in fetches


def test_hotpath_follows_callback_registration():
    findings = run_rules(SourceTree(_ENGINE_FIXTURE), ["hotpath"])
    sleeps = {f.qualname for f in findings if f.check == "serialization"}
    assert "InferenceEngine._cb" in sleeps


def test_hotpath_contract_flags_missing_tails():
    findings = run_rules(SourceTree(_ENGINE_FIXTURE), ["hotpath"])
    contract = {f.qualname for f in findings if f.check == "contract"}
    # the fixture has neither _step_pipelined nor the sync tails
    assert "InferenceEngine._step_pipelined" in contract
    assert any(q.endswith("._resolve_mixed") for q in contract)


# ----------------------------------------------------------- acceptance

# The hand-curated allowlist the analyzer replaced (tests/
# test_hotpath_guard.py at its last hand-maintained revision).  The
# call-graph discovery must cover every one of these WITHOUT any of them
# being listed in the rule — if a rename breaks an edge, this diff test
# names exactly the function that fell out of coverage.
LEGACY_HOT_PATH_FUNCTIONS = (
    "step", "_step_pipelined", "_pipe_issue", "_issue_decode",
    "_issue_mixed", "_issue_spec_mixed", "_fill_chunk_lanes",
    "_issue_admit_batch", "_spill_flush", "_issue_restore",
    "_dispatch_restore_group", "_issue_model_load", "_park_awaiting_model",
    "_note_evicted", "_register_prompt_pages", "_maybe_preempt",
    "_issue_preempt_swap", "_preempt_replay", "_service_swapped",
    "_resume_swapped", "_mixed_grid_counters",
)


def test_step_reachability_covers_legacy_hot_path_tuple():
    tree = SourceTree.load(repo_root())
    graph = CallGraph(tree)
    reach = hotpath_rule.step_reachable(graph)
    names = {graph.nodes[nid].name for nid in reach
             if graph.nodes[nid].path == hotpath_rule.ENGINE}
    missing = [n for n in LEGACY_HOT_PATH_FUNCTIONS if n not in names]
    assert not missing, (
        f"call-graph discovery lost legacy hot-path coverage: {missing}")
    # and it genuinely discovers MORE than the hand-list ever did
    assert len(names) > len(LEGACY_HOT_PATH_FUNCTIONS)


def test_rule_source_hand_lists_no_hot_path_helper():
    """The rule must keep discovering the hot path, not enumerate it:
    none of the legacy names (beyond the two roots) may appear in the
    rule's source."""
    src = pathlib.Path(hotpath_rule.__file__).read_text()
    roots = {"step", "_step_pipelined"}
    listed = [n for n in LEGACY_HOT_PATH_FUNCTIONS
              if n not in roots and f'"{n}"' in src]
    assert not listed, f"hand-listed hot-path names crept back in: {listed}"


# ------------------------------------------------------ exceptions fixtures

def test_exceptions_engine_strict_vs_repo_lenient():
    lenient = (
        "import logging\n"
        "log = logging.getLogger()\n"
        "def f():\n"
        "    try:\n"
        "        g()\n"
        "    except Exception:\n"
        "        log.exception('boom')\n"
    )
    tree = SourceTree({"arks_tpu/engine/x.py": lenient,
                       "arks_tpu/gateway/x.py": lenient})
    findings = run_rules(tree, ["exceptions"])
    paths = {f.path for f in findings}
    # log.exception is an observable swallow outside the engine only
    assert "arks_tpu/engine/x.py" in paths
    assert "arks_tpu/gateway/x.py" not in paths


def test_exceptions_fault_api_and_narrow_handlers_pass():
    tree = SourceTree({"arks_tpu/engine/x.py": (
        "def f():\n"
        "    try:\n"
        "        g()\n"
        "    except Exception as e:\n"
        "        swallowed('site', e)\n"
        "    try:\n"
        "        g()\n"
        "    except ValueError:\n"
        "        pass\n"
        "    try:\n"
        "        g()\n"
        "    except Exception:\n"
        "        raise\n"
    )})
    assert not run_rules(tree, ["exceptions"])


def test_exceptions_flags_bare_swallow():
    tree = SourceTree({"arks_tpu/control/x.py": (
        "def f():\n"
        "    try:\n"
        "        g()\n"
        "    except Exception:\n"
        "        pass\n"
    )})
    findings = run_rules(tree, ["exceptions"])
    assert [f.check for f in findings] == ["broad-swallow"]


# ----------------------------------------------------------- knobs fixtures

_REGISTRY_FIXTURE = (
    "def _k(*a, **kw):\n"
    "    pass\n"
    '_k("ARKS_GOOD", "int", "4", "doc", "engine")\n'
)


def _knob_tree(body: str) -> SourceTree:
    return SourceTree({
        "arks_tpu/utils/knobs.py": _REGISTRY_FIXTURE,
        "arks_tpu/x.py": body,
    })


def test_knobs_flags_raw_env_read_and_write():
    findings = run_rules(_knob_tree(
        "import os\n"
        'a = os.environ.get("ARKS_GOOD", "4")\n'
        'os.environ["ARKS_GOOD"] = "5"\n'
    ), ["knobs"])
    checks = sorted(f.check for f in findings if f.severity == "error")
    assert checks == ["raw-env-read", "raw-env-write"]


def test_knobs_accessor_with_registered_name_passes():
    findings = run_rules(_knob_tree(
        "from arks_tpu.utils import knobs\n"
        'a = knobs.get_int("ARKS_GOOD")\n'
    ), ["knobs"])
    assert not [f for f in findings if f.severity == "error"]


def test_knobs_flags_unregistered_name():
    findings = run_rules(_knob_tree(
        "from arks_tpu.utils import knobs\n"
        'a = knobs.get_int("ARKS_NOPE")\n'
    ), ["knobs"])
    assert "unregistered-knob" in {f.check for f in findings}


def test_knobs_module_constant_resolves_statically():
    findings = run_rules(_knob_tree(
        "from arks_tpu.utils import knobs\n"
        'ENV = "ARKS_GOOD"\n'
        "def f():\n"
        "    return knobs.get_int(ENV)\n"
    ), ["knobs"])
    assert "dynamic-knob-name" not in {f.check for f in findings}


def test_knobs_dynamic_name_warns():
    findings = run_rules(_knob_tree(
        "from arks_tpu.utils import knobs\n"
        "def f(name):\n"
        "    return knobs.get_int(name)\n"
    ), ["knobs"])
    dyn = [f for f in findings if f.check == "dynamic-knob-name"]
    assert dyn and all(f.severity == "warn" for f in dyn)


def test_knobs_unused_registration_warns():
    findings = run_rules(SourceTree({
        "arks_tpu/utils/knobs.py": _REGISTRY_FIXTURE,
    }), ["knobs"])
    unused = [f for f in findings if f.check == "unused-knob"]
    assert [f.detail for f in unused] == ["ARKS_GOOD"]
    assert all(f.severity == "warn" for f in unused)


# ------------------------------------------------------ tracepurity fixtures

def test_tracepurity_flags_host_state_in_traced_functions():
    findings = run_rules(SourceTree({"arks_tpu/ops/x.py": (
        "import time, os\n"
        "import jax\n"
        "@jax.jit\n"
        "def traced(x):\n"
        "    t = time.time()\n"
        '    e = os.environ.get("ARKS_GOOD")\n'
        "    return x\n"
        "def kernel(ref):\n"
        "    import numpy as np\n"
        "    return np.random.rand()\n"
        "def launch():\n"
        "    return pl.pallas_call(kernel)\n"
        "def untraced():\n"
        "    return time.time()\n"
    )}), ["tracepurity"])
    by_fn = {}
    for f in findings:
        by_fn.setdefault(f.qualname, set()).add(f.check)
    assert by_fn.get("traced") == {"wall-clock", "host-state"}
    assert by_fn.get("kernel") == {"host-rng"}
    assert "untraced" not in by_fn


# --------------------------------------------------------- metrics fixtures

def test_metrics_conventions_and_duplicates():
    findings = run_rules(SourceTree({
        "arks_tpu/a.py": (
            "class AMetrics:\n"
            "    def __init__(self, reg):\n"
            '        self.c = reg.counter("requests_total", "d")\n'
            '        self.bad = reg.counter("requests_seconds", "d")\n'
            '        self.g = reg.gauge("depth_total", "d")\n'
        ),
        "arks_tpu/b.py": (
            "class BMetrics:\n"
            "    def __init__(self, reg):\n"
            '        self.c = reg.counter("requests_total", "d")\n'
        ),
    }), ["metrics"])
    checks = sorted(f.check for f in findings)
    assert checks.count("duplicate-family") == 1
    # counter without _total AND gauge with _total are both conventions
    assert checks.count("name-convention") == 2


# ----------------------------------------------------- CLI / baseline / docs

def test_cli_exits_zero_on_the_real_tree_under_ten_seconds():
    t0 = time.monotonic()
    proc = subprocess.run(
        [sys.executable, "-m", "arks_tpu.analysis", "--all", "--json"],
        cwd=repo_root(), capture_output=True, text=True, timeout=60)
    elapsed = time.monotonic() - t0
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert elapsed < 10, f"arkslint took {elapsed:.1f}s (budget 10s)"
    payload = json.loads(proc.stdout)
    assert payload["counts"]["errors"] == 0
    assert payload["counts"]["stale"] == 0


def test_baseline_is_reviewed_and_bounded():
    baseline = Baseline.load(
        repo_root() / "tools" / "arkslint-baseline.json")
    assert baseline.entries, "baseline file went missing"
    assert len(baseline.entries) <= MAX_SUPPRESSIONS
    for e in baseline.entries:
        assert e["reason"] and "TODO" not in e["reason"], e


def test_baseline_has_no_stale_entries():
    findings = run_rules(SourceTree.load(repo_root()))
    baseline = Baseline.load(
        repo_root() / "tools" / "arkslint-baseline.json")
    _active, _suppressed, stale = baseline.apply(findings)
    assert not stale, f"stale suppressions: {stale}"


def test_generated_knob_docs_are_in_sync():
    """docs/configuration.md is generated (``--gen-knob-docs``); a knob
    edit without regeneration fails here, not in review."""
    from arks_tpu.utils import knobs
    on_disk = (repo_root() / "docs" / "configuration.md").read_text()
    assert on_disk == knobs.render_markdown(), (
        "docs/configuration.md is stale — run "
        "python -m arks_tpu.analysis --gen-knob-docs")
