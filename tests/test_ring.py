"""Ring attention (context parallelism) vs the single-device oracle.

The reference has no sequence-parallel code at all (SURVEY.md §5); here it
is a first-class mesh axis, testable on the virtual 8-device CPU mesh.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from arks_tpu.models import get_config
from arks_tpu.models import transformer as tf
from arks_tpu.ops.attention import prefill_attention
from arks_tpu.parallel.mesh import make_mesh
from arks_tpu.parallel.ring import ring_prefill_attention


@pytest.mark.parametrize("cp,h,hkv", [(8, 4, 4), (4, 8, 2), (2, 4, 1)])
def test_ring_attention_matches_dense_causal(cp, h, hkv):
    b, t, d = 2, 64, 16
    key = jax.random.PRNGKey(0)
    ks = jax.random.split(key, 3)
    q = jax.random.normal(ks[0], (b, t, h, d), jnp.float32)
    k = jax.random.normal(ks[1], (b, t, hkv, d), jnp.float32)
    v = jax.random.normal(ks[2], (b, t, hkv, d), jnp.float32)
    ref = prefill_attention(q, k, v)
    mesh = make_mesh(tensor_parallel=1, context_parallel=cp,
                     devices=jax.devices()[:cp])
    got = ring_prefill_attention(q, k, v, mesh, seq_axis="seq")
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_prefill_context_parallel_matches_single_device():
    """Full model prefill with T sharded over the seq axis: logits and the
    KV destined for the cache must match the unsharded path."""
    cfg = get_config("tiny-gqa")
    params = tf.init_params(cfg, jax.random.PRNGKey(0), jnp.float32)
    t, n = 32, 30
    ids = jax.random.randint(jax.random.PRNGKey(1), (1, t), 0, cfg.vocab_size)
    lengths = jnp.asarray([n], jnp.int32)

    ref_logits, ref_k, ref_v = tf.prefill(params, cfg, ids, lengths)
    mesh = make_mesh(tensor_parallel=1, context_parallel=8)
    got_logits, got_k, got_v = tf.prefill(params, cfg, ids, lengths, mesh,
                                          seq_axis="seq")
    np.testing.assert_allclose(np.asarray(got_logits), np.asarray(ref_logits),
                               rtol=5e-4, atol=5e-4)
    np.testing.assert_allclose(np.asarray(got_k), np.asarray(ref_k),
                               rtol=5e-5, atol=5e-5)
    np.testing.assert_allclose(np.asarray(got_v), np.asarray(ref_v),
                               rtol=5e-5, atol=5e-5)


def test_prefill_seq_plus_tensor_parallel():
    """seq and model axes together: long-context prefill on a TP slice."""
    cfg = get_config("tiny-gqa")
    params = tf.init_params(cfg, jax.random.PRNGKey(0), jnp.float32)
    ids = jax.random.randint(jax.random.PRNGKey(2), (1, 32), 0, cfg.vocab_size)
    lengths = jnp.asarray([32], jnp.int32)
    ref_logits, _, _ = tf.prefill(params, cfg, ids, lengths)

    mesh = make_mesh(tensor_parallel=2, context_parallel=4)
    params_s = tf.shard_params(params, cfg, mesh)
    got_logits, _, _ = tf.prefill(params_s, cfg, ids, lengths, mesh,
                                  seq_axis="seq")
    np.testing.assert_allclose(np.asarray(got_logits), np.asarray(ref_logits),
                               rtol=5e-4, atol=5e-4)
