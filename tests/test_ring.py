"""Ring attention (context parallelism) vs the single-device oracle.

The reference has no sequence-parallel code at all (SURVEY.md §5); here it
is a first-class mesh axis, testable on the virtual 8-device CPU mesh.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from arks_tpu.models import get_config
from arks_tpu.models import transformer as tf
from arks_tpu.ops.attention import prefill_attention
from arks_tpu.parallel.mesh import make_mesh
from arks_tpu.parallel.ring import ring_prefill_attention


@pytest.mark.parametrize("cp,h,hkv", [(8, 4, 4), (4, 8, 2), (2, 4, 1)])
def test_ring_attention_matches_dense_causal(cp, h, hkv):
    b, t, d = 2, 64, 16
    key = jax.random.PRNGKey(0)
    ks = jax.random.split(key, 3)
    q = jax.random.normal(ks[0], (b, t, h, d), jnp.float32)
    k = jax.random.normal(ks[1], (b, t, hkv, d), jnp.float32)
    v = jax.random.normal(ks[2], (b, t, hkv, d), jnp.float32)
    ref = prefill_attention(q, k, v)
    mesh = make_mesh(tensor_parallel=1, context_parallel=cp,
                     devices=jax.devices()[:cp])
    got = ring_prefill_attention(q, k, v, mesh, seq_axis="seq")
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_prefill_context_parallel_matches_single_device():
    """Full model prefill with T sharded over the seq axis: logits and the
    KV destined for the cache must match the unsharded path."""
    cfg = get_config("tiny-gqa")
    params = tf.init_params(cfg, jax.random.PRNGKey(0), jnp.float32)
    t, n = 32, 30
    ids = jax.random.randint(jax.random.PRNGKey(1), (1, t), 0, cfg.vocab_size)
    lengths = jnp.asarray([n], jnp.int32)

    ref_logits, ref_k, ref_v = tf.prefill(params, cfg, ids, lengths)
    mesh = make_mesh(tensor_parallel=1, context_parallel=8)
    got_logits, got_k, got_v = tf.prefill(params, cfg, ids, lengths, mesh,
                                          seq_axis="seq")
    np.testing.assert_allclose(np.asarray(got_logits), np.asarray(ref_logits),
                               rtol=5e-4, atol=5e-4)
    np.testing.assert_allclose(np.asarray(got_k), np.asarray(ref_k),
                               rtol=5e-5, atol=5e-5)
    np.testing.assert_allclose(np.asarray(got_v), np.asarray(ref_v),
                               rtol=5e-5, atol=5e-5)


def test_prefill_seq_plus_tensor_parallel():
    """seq and model axes together: long-context prefill on a TP slice."""
    cfg = get_config("tiny-gqa")
    params = tf.init_params(cfg, jax.random.PRNGKey(0), jnp.float32)
    ids = jax.random.randint(jax.random.PRNGKey(2), (1, 32), 0, cfg.vocab_size)
    lengths = jnp.asarray([32], jnp.int32)
    ref_logits, _, _ = tf.prefill(params, cfg, ids, lengths)

    mesh = make_mesh(tensor_parallel=2, context_parallel=4)
    params_s = tf.shard_params(params, cfg, mesh)
    got_logits, _, _ = tf.prefill(params_s, cfg, ids, lengths, mesh,
                                  seq_axis="seq")
    np.testing.assert_allclose(np.asarray(got_logits), np.asarray(ref_logits),
                               rtol=5e-4, atol=5e-4)


def test_serving_engine_with_context_parallelism():
    """Ring attention is reachable FROM SERVING: an engine configured with
    context_parallel=2 prefills with T sharded over the 'seq' axis and
    produces the same greedy tokens as the single-device engine."""
    from arks_tpu.engine import (
        EngineConfig, InferenceEngine, Request, SamplingParams)
    from arks_tpu.engine.tokenizer import ByteTokenizer
    from arks_tpu.models import get_config

    cfg = get_config("tiny")
    prompt = [int(x) % cfg.vocab_size for x in range(5, 37)]  # 32 tokens

    def run(cp):
        ecfg = EngineConfig(model="tiny", num_slots=2, max_cache_len=64,
                            prefill_buckets=(16, 32), steps_per_dispatch=4,
                            context_parallel=cp, prefix_cache_mb=0)
        eng = InferenceEngine(cfg, ecfg, ByteTokenizer())
        req = Request("r", prompt, SamplingParams(max_tokens=6, temperature=0.0,
                                                  ignore_eos=True))
        eng.add_request(req)
        for _ in range(100):
            eng.step(block_s=0.01)
            if eng.num_running == 0 and eng._queue.empty() and not eng._prefilling:
                break
        ids = []
        while True:
            out = req.outputs.get(timeout=60)
            ids.extend(out.token_ids)
            if out.finished:
                return ids, out

    ids_cp, fin_cp = run(2)
    ids_one, _ = run(1)
    assert fin_cp.num_prompt_tokens == 32
    assert ids_cp == ids_one


def _run_cp_engine(prompts, cp, layout, sequential=False):
    """Drive an engine at (cp, kv_layout) over ``prompts``; returns
    (per-prompt greedy ids, paged prefix hit tokens).  ``sequential``
    waits out each request before adding the next (so earlier prompts'
    pages are registered before later ones admit — concurrent admission
    would batch them into one dispatch)."""
    from arks_tpu.engine import (
        EngineConfig, InferenceEngine, Request, SamplingParams)
    from arks_tpu.engine.tokenizer import ByteTokenizer

    cfg = get_config("tiny")
    ecfg = EngineConfig(model="tiny", num_slots=4, max_cache_len=64,
                        prefill_buckets=(16, 32), steps_per_dispatch=4,
                        context_parallel=cp, prefix_cache_mb=0,
                        kv_layout=layout, prefill_chunk=16)
    eng = InferenceEngine(cfg, ecfg, ByteTokenizer())
    eng.start()
    outs = []
    try:
        def drain(r):
            ids = []
            while True:
                out = r.outputs.get(timeout=120)
                ids.extend(out.token_ids)
                if out.finished:
                    return ids

        reqs = []
        for i, p in enumerate(prompts):
            r = Request(f"r{i}", list(p), SamplingParams(
                max_tokens=6, temperature=0.0, ignore_eos=True))
            eng.add_request(r)
            if sequential:
                outs.append(drain(r))
            else:
                reqs.append(r)
        outs.extend(drain(r) for r in reqs)
        hit = eng._alloc.hit_tokens if layout == "paged" else 0
    finally:
        eng.stop()
    return outs, hit


def test_engine_paged_with_context_parallelism():
    """The paged layout composes with cp (the round-3 blocker is lifted):
    one-shot ring-sharded prefill inserts through the block tables, decode
    rides the seq-replicated pool, and greedy output matches the cp=1 slot
    oracle."""
    cfg = get_config("tiny")
    prompts = ([int(x) % cfg.vocab_size for x in range(5, 37)],
               [5, 6, 7, 8, 9, 10, 11, 12],
               [int(x) % cfg.vocab_size for x in range(3, 48)])
    assert _run_cp_engine(prompts, 2, "paged")[0] == \
        _run_cp_engine(prompts, 1, "slot")[0]


def test_engine_paged_cp_prefix_sharing():
    """On-device prefix sharing keeps working under cp: a second prompt
    with a shared prefix points its table at the first prompt's pages and
    only the tail chunk-prefills (unsharded over seq — only one-shot
    prefill rides the ring; chunk tails are bounded dispatches)."""
    prompts = ([7] * 33, [7] * 33 + [9, 10, 11])
    ref, _ = _run_cp_engine(prompts, 1, "slot", sequential=True)
    got, hit = _run_cp_engine(prompts, 2, "paged", sequential=True)
    assert got == ref
    assert hit >= 32  # two full 16-token pages reused on device


def test_cp_extends_one_shot_window_for_long_prompts():
    """With context parallelism the one-shot buckets extend to the full
    cache window, so LONG prompts ride the sharded ring instead of falling
    into the unsharded chunked path — the workload cp exists for."""
    from arks_tpu.engine import (
        EngineConfig, InferenceEngine, Request, SamplingParams)
    from arks_tpu.engine.tokenizer import ByteTokenizer
    from arks_tpu.models import get_config

    cfg = get_config("tiny")
    ecfg = EngineConfig(model="tiny", num_slots=2, max_cache_len=64,
                        prefill_buckets=(16, 32), steps_per_dispatch=4,
                        context_parallel=2, prefix_cache_mb=0)
    eng = InferenceEngine(cfg, ecfg, ByteTokenizer())
    assert eng._buckets[-1] == 64  # extended beyond the configured 32
    prompt = [int(x) % cfg.vocab_size for x in range(3, 48)]  # 45 > old max
    req = Request("long", prompt, SamplingParams(max_tokens=3, temperature=0.0,
                                                 ignore_eos=True))
    eng.add_request(req)
    # One-shot admission: never chunk-queued at any point (admission may
    # resolve deferred, so drive steps until the request completes).
    for _ in range(100):
        eng.step(block_s=0.01)
        assert not eng._prefilling
        if eng.num_running == 0 and eng._queue.empty():
            break
    ids = []
    while True:
        out = req.outputs.get(timeout=60)
        ids.extend(out.token_ids)
        if out.finished:
            break
    assert out.num_prompt_tokens == 45 and len(ids) == 3
