"""Unit tests for the device-HBM weight pool (engine.model_pool).

The pool is pure host-side bookkeeping — params here are plain numpy
trees, so these tests exercise the budget/LRU/refcount/ticket state
machine without touching a device.
"""

import threading
import time

import numpy as np
import pytest

from arks_tpu.engine.model_pool import (
    LoadTicket, ModelPool, PoolFullError, tree_bytes)

MB = 1 << 20


def _params(mb):
    """A param tree of exactly ``mb`` MiB of logical bytes."""
    return {"w": np.zeros((mb, MB // 4), dtype=np.float32)}


def test_tree_bytes_counts_logical_leaf_bytes():
    assert tree_bytes(_params(3)) == 3 * MB
    assert tree_bytes({"a": _params(1), "b": _params(2)}) == 3 * MB


def test_register_is_idempotent_and_adopt_makes_resident():
    pool = ModelPool(hbm_budget_mb=0)
    e1 = pool.register("m", cfg="cfg-a", model_path="/p")
    e2 = pool.register("m", cfg="ignored", pinned=True)
    assert e1 is e2 and e1.pinned and e1.model_path == "/p"

    pool.adopt("m", "cfg-a", _params(2))
    snap = {s["name"]: s for s in pool.snapshot()}
    assert snap["m"]["state"] == "resident"
    assert snap["m"]["resident_bytes"] == 2 * MB
    assert snap["m"]["pinned"] is True
    assert pool.params_of("m")["w"].shape[0] == 2


def test_ensure_returns_ticket_then_resident_entry():
    pool = ModelPool(hbm_budget_mb=0)
    gate = threading.Event()

    def loader():
        gate.wait(10)
        return _params(1)

    pool.register("m", "cfg", loader=loader)
    t = pool.ensure("m")
    assert isinstance(t, LoadTicket) and not t.event.is_set()
    # Re-ensuring while the load is in flight returns the SAME ticket —
    # the engine polls it from the step loop.
    assert pool.ensure("m") is t
    gate.set()
    assert t.event.wait(10) and t.error is None
    e = pool.ensure("m")
    assert not isinstance(e, LoadTicket)
    assert e.state == "resident" and e.cold_starts == 1
    # load() is the blocking wrapper over the same path.
    assert pool.load("m", timeout=10)["w"].shape[0] == 1


def test_ensure_unknown_model_raises_keyerror():
    pool = ModelPool(hbm_budget_mb=0)
    with pytest.raises(KeyError):
        pool.ensure("nope")
    pool.register("m", "cfg")  # registered but no loader and no params
    with pytest.raises(KeyError):
        pool.ensure("m")


def test_loader_failure_surfaces_on_the_ticket():
    pool = ModelPool(hbm_budget_mb=0)

    def boom():
        raise OSError("disk gone")

    pool.register("m", "cfg", loader=boom)
    t = pool.ensure("m")
    assert t.event.wait(10)
    assert "disk gone" in t.error
    assert pool.entry("m").state == "evicted"
    with pytest.raises(RuntimeError, match="disk gone"):
        pool.load("m", timeout=10)


def test_budget_evicts_lru_idle_unpinned():
    pool = ModelPool(hbm_budget_mb=3)
    evicted = []
    pool.on_evict = evicted.append
    pool.adopt("old", "cfg", _params(1))
    time.sleep(0.01)
    pool.adopt("new", "cfg", _params(1))
    pool.register("big", "cfg", loader=lambda: _params(2))
    assert pool.load("big", timeout=10)["w"].shape[0] == 2
    # Only the LRU entry goes; "new" still fits next to "big".
    assert evicted == ["old"]
    snap = {s["name"]: s["state"] for s in pool.snapshot()}
    assert snap == {"old": "evicted", "new": "resident", "big": "resident"}
    # The evicted entry remembers its size, so a reload makes room
    # BEFORE streaming (and can evict in turn).
    assert pool.entry("old").nbytes == 1 * MB


def test_pinned_and_in_use_models_never_evicted():
    pool = ModelPool(hbm_budget_mb=3)
    pool.adopt("flag", "cfg", _params(1), pinned=True)
    pool.adopt("busy", "cfg", _params(1))
    pool.acquire("busy")  # engine is decoding with it
    pool.register("big", "cfg", loader=lambda: _params(2))
    with pytest.raises(PoolFullError):
        pool.load("big", timeout=10)
    snap = {s["name"]: s["state"] for s in pool.snapshot()}
    assert snap["flag"] == "resident" and snap["busy"] == "resident"
    # Releasing the refcount frees "busy" for eviction; the reload works.
    pool.release("busy")
    assert pool.load("big", timeout=10)["w"].shape[0] == 2
    assert pool.entry("busy").state == "evicted"


def test_pool_full_error_rides_the_ticket_as_exhausted():
    pool = ModelPool(hbm_budget_mb=1)
    pool.adopt("flag", "cfg", _params(1), pinned=True)
    pool.register("big", "cfg", loader=lambda: _params(2))
    t = pool.ensure("big")
    assert t.event.wait(10)
    assert "model_pool_exhausted" in t.error


def test_acquire_requires_resident_and_refcounts_nest():
    pool = ModelPool(hbm_budget_mb=0)
    pool.register("m", "cfg", loader=lambda: _params(1))
    with pytest.raises(RuntimeError, match="not resident"):
        pool.acquire("m")
    pool.load("m", timeout=10)
    pool.acquire("m")
    pool.acquire("m")
    assert pool.entry("m").refcount == 2
    pool.release("m")
    pool.release("m")
    pool.release("m")  # over-release is a no-op, never negative
    assert pool.entry("m").refcount == 0


def test_budget_env_validation(monkeypatch):
    monkeypatch.setenv("ARKS_MODEL_POOL_HBM_MB", "not-a-number")
    with pytest.raises(ValueError, match="ARKS_MODEL_POOL_HBM_MB"):
        ModelPool()
    monkeypatch.setenv("ARKS_MODEL_POOL_HBM_MB", "64")
    assert ModelPool().budget_bytes == 64 * MB
    with pytest.raises(ValueError):
        ModelPool(hbm_budget_mb=-1)
