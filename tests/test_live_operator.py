"""Live-operator mode (control.live): the existing controllers driving a
(fake) Kubernetes apiserver — CRs in, owned StatefulSets/Services out,
status projected back, rolling updates sequenced across groups, deletion
finalizer-gated.  The envtest-tier behaviors the reference only scaffolds
(SURVEY.md §4)."""

import time

import pytest

from arks_tpu.control.k8s_client import ApiError, FakeKubeApi
from arks_tpu.control.live import FINALIZER, GV, LiveOperator


def wait_for(predicate, timeout=30.0, interval=0.05):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        v = predicate()
        if v:
            return v
        time.sleep(interval)
    raise AssertionError("condition not met within timeout")


@pytest.fixture()
def live(tmp_path):
    api = FakeKubeApi()
    op = LiveOperator(api, models_root=str(tmp_path / "models"),
                      interval_s=0.1)
    op.start()
    yield api, op
    op.stop()


def _cr(kind: str, name: str, spec: dict, ns: str = "default") -> dict:
    return {"apiVersion": GV, "kind": kind,
            "metadata": {"name": name, "namespace": ns}, "spec": spec}


def _mk_app(api, name="app1", replicas=2, served="m-served"):
    api.create(GV, "arksmodels", "default",
               _cr("ArksModel", "m1", {"model": "org/m"}))
    api.create(GV, "arksapplications", "default", _cr(
        "ArksApplication", name, {
            "replicas": replicas, "size": 1, "runtime": "jax",
            "model": {"name": "m1"}, "servedModelName": served,
            "modelConfig": "tiny",
        }))


def _sts_names(api):
    return sorted(s["metadata"]["name"]
                  for s in api.list("apps/v1", "statefulsets"))


def _mark_ready(api, name, ready=1):
    api.patch("apps/v1", "statefulsets", "default", name,
              {"status": {"readyReplicas": ready}}, subresource="status")


def test_application_cr_to_statefulsets_and_back(live):
    """VERDICT acceptance: Application through the API -> StatefulSet/
    Service objects appear; readiness flows back into the CR's
    status.readyReplicas."""
    api, op = live
    _mk_app(api, replicas=2)

    wait_for(lambda: _sts_names(api) == ["arks-app1-0", "arks-app1-1"])
    svcs = sorted(s["metadata"]["name"] for s in api.list("v1", "services"))
    assert svcs == ["arks-app1-0", "arks-app1-1"]

    # Model went Ready (existing-storage path) and its status is projected.
    m = wait_for(lambda: api.get(GV, "arksmodels", "default", "m1"))
    wait_for(lambda: (api.get(GV, "arksmodels", "default", "m1")
                      .get("status", {}).get("phase")) == "Ready")

    # App not ready yet: no STS reports ready pods.
    app = api.get(GV, "arksapplications", "default", "app1")
    assert FINALIZER in app["metadata"]["finalizers"]
    wait_for(lambda: (api.get(GV, "arksapplications", "default", "app1")
                      .get("status", {}).get("phase")) == "Creating")

    _mark_ready(api, "arks-app1-0")
    _mark_ready(api, "arks-app1-1")
    wait_for(lambda: (api.get(GV, "arksapplications", "default", "app1")
                      .get("status", {}).get("readyReplicas")) == 2)
    assert (api.get(GV, "arksapplications", "default", "app1")
            ["status"]["phase"]) == "Running"


def test_endpoint_routes_projected(live):
    api, op = live
    _mk_app(api, served="ep-model")
    api.create(GV, "arksendpoints", "default",
               _cr("ArksEndpoint", "ep-model", {"defaultWeight": 2}))
    wait_for(lambda: _sts_names(api))
    for n in _sts_names(api):
        _mark_ready(api, n)
    routes = wait_for(lambda: (api.get(GV, "arksendpoints", "default", "ep-model")
                               .get("status", {}).get("routes")))
    assert routes[0]["weight"] == 2
    assert "arks-app1-0-0.arks-app1-0" in routes[0]["backend"]["addresses"][0]


def test_live_rolling_update_sequenced(live):
    """A spec change rolls ONE group's StatefulSet at a time, gated on the
    previous group reporting ready again (the cross-group maxUnavailable=1
    static manifests cannot express)."""
    api, op = live
    _mk_app(api, replicas=2)
    wait_for(lambda: len(_sts_names(api)) == 2)
    for n in _sts_names(api):
        _mark_ready(api, n)
    wait_for(lambda: (api.get(GV, "arksapplications", "default", "app1")
                      .get("status", {}).get("readyReplicas")) == 2)

    def revision(name):
        sts = api.get("apps/v1", "statefulsets", "default", name)
        return sts["spec"]["template"]["metadata"]["annotations"]["arks.ai/revision"]

    rev0 = revision("arks-app1-0")
    api.patch(GV, "arksapplications", "default", "app1",
              {"spec": {"runtimeCommonArgs": ["--max-model-len", "2048"]}})

    # Group 0 rolls first (the fake apiserver zeroes its readiness on the
    # template change, as the real controller-manager restart would)...
    wait_for(lambda: revision("arks-app1-0") != rev0)
    new_rev = revision("arks-app1-0")
    # ...and while it is not ready again, group 1 must HOLD the old revision.
    time.sleep(1.0)  # several reconcile cycles
    assert revision("arks-app1-1") == rev0

    # Group 0 back up -> group 1 rolls.
    _mark_ready(api, "arks-app1-0", ready=1)
    wait_for(lambda: revision("arks-app1-1") == new_rev)


def test_deletion_finalizer_gated(live):
    api, op = live
    _mk_app(api, replicas=1)
    wait_for(lambda: _sts_names(api) == ["arks-app1-0"])

    api.delete(GV, "arksapplications", "default", "app1")
    # Finalizer holds the CR until the store teardown removed the workload.
    wait_for(lambda: api.get(GV, "arksapplications", "default", "app1") is None)
    assert _sts_names(api) == []
    assert api.list("v1", "services") == []


def test_rendered_pods_carry_gang_contract(live):
    """Live-mode pods must match the gitops renderer's mechanics: models
    PVC mount, TPU nodeSelector/topology/chip requests via the shape
    table, and the jax.distributed env contract with per-pod process
    index — for a size>1 TPU gang."""
    api, op = live
    api.create(GV, "arksmodels", "default",
               _cr("ArksModel", "m1", {"model": "org/m"}))
    api.create(GV, "arksapplications", "default", _cr(
        "ArksApplication", "tpuapp", {
            "replicas": 1, "size": 2, "runtime": "jax",
            "model": {"name": "m1"}, "servedModelName": "tpu-served",
            "modelConfig": "qwen2.5-7b", "accelerator": "tpu-v5p-16",
        }))
    sts = wait_for(lambda: api.get("apps/v1", "statefulsets", "default",
                                   "arks-tpuapp-0"))
    pod = sts["spec"]["template"]["spec"]
    assert pod["nodeSelector"] == {
        "cloud.google.com/gke-tpu-accelerator": "tpu-v5p-slice",
        "cloud.google.com/gke-tpu-topology": "2x2x2"}
    c = pod["containers"][0]
    assert c["resources"]["requests"]["google.com/tpu"] == "4"
    env = {e["name"]: e for e in c["env"]}
    assert env["ARKS_NUM_PROCESSES"]["value"] == "2"
    assert "pod-index" in str(env["ARKS_PROCESS_ID"]["valueFrom"])
    assert env["ARKS_COORDINATOR_ADDRESS"]["value"].startswith(
        "arks-tpuapp-0-0.arks-tpuapp-0")
    assert "ARKS_GANG_SECRET" in env
    # The SHARED models PVC (the one the operator downloads into) mounted
    # read-only at the reserved path.
    assert pod["volumes"][0]["persistentVolumeClaim"]["claimName"] == "models"
    assert c["volumeMounts"][0]["mountPath"] == "/models"


def test_force_removed_cr_tears_down(live):
    """A CR removed from the apiserver without our finalizer running (e.g.
    kubectl patch to strip finalizers) still tears down owned objects."""
    api, op = live
    _mk_app(api, replicas=1)
    wait_for(lambda: _sts_names(api) == ["arks-app1-0"])
    # Strip the finalizer and delete in one shot.
    api.patch(GV, "arksapplications", "default", "app1",
              {"metadata": {"finalizers": []}})
    api.delete(GV, "arksapplications", "default", "app1")
    assert api.get(GV, "arksapplications", "default", "app1") is None
    wait_for(lambda: _sts_names(api) == [])


def test_live_instance_spec_and_podgroup(live):
    """instanceSpec flows from the CR into live-rendered pods, and a
    podGroupPolicy yields a PodGroup with minMember = gang size plus the
    coscheduling pod label."""
    api, op = live
    api.create(GV, "arksmodels", "default",
               _cr("ArksModel", "m1", {"model": "org/m"}))
    api.create(GV, "arksapplications", "default", _cr(
        "ArksApplication", "gapp", {
            "replicas": 1, "size": 2, "runtime": "jax",
            "model": {"name": "m1"}, "servedModelName": "g-served",
            "modelConfig": "tiny", "accelerator": "tpu-v5p-16",
            "instanceSpec": {
                "env": [{"name": "HF_HOME", "value": "/tmp/hf"}],
                "tolerations": [{"key": "google.com/tpu",
                                 "operator": "Exists"}],
            },
            "podGroupPolicy": {"kubeScheduling": {
                "scheduleTimeoutSeconds": 120}},
        }))
    sts = wait_for(lambda: api.get("apps/v1", "statefulsets", "default",
                                   "arks-gapp-0"))
    pod = sts["spec"]["template"]["spec"]
    env = {e["name"]: e.get("value") for e in pod["containers"][0]["env"]}
    assert env["HF_HOME"] == "/tmp/hf"
    assert pod["tolerations"][0]["key"] == "google.com/tpu"
    labels = sts["spec"]["template"]["metadata"]["labels"]
    assert labels["scheduling.x-k8s.io/pod-group"] == "arks-gapp-0"
    pg = wait_for(lambda: api.get("scheduling.x-k8s.io/v1alpha1", "podgroups",
                                  "default", "arks-gapp-0"))
    assert pg["spec"]["minMember"] == 2
    assert pg["spec"]["scheduleTimeoutSeconds"] == 120

    # Gang-size changes must propagate into minMember — a stale value above
    # the real size would deadlock the coscheduling plugin forever.
    api.patch(GV, "arksapplications", "default", "gapp", {"spec": {"size": 1}})
    wait_for(lambda: api.get("scheduling.x-k8s.io/v1alpha1", "podgroups",
                             "default", "arks-gapp-0")["spec"]["minMember"] == 1)

    # Removing the policy must delete the PodGroup, not orphan it.
    api.patch(GV, "arksapplications", "default", "gapp",
              {"spec": {"podGroupPolicy": None}})
    wait_for(lambda: api.get("scheduling.x-k8s.io/v1alpha1", "podgroups",
                             "default", "arks-gapp-0") is None)


def test_live_invalid_instance_spec_fails_precheck(live):
    api, op = live
    api.create(GV, "arksmodels", "default",
               _cr("ArksModel", "m1", {"model": "org/m"}))
    api.create(GV, "arksapplications", "default", _cr(
        "ArksApplication", "bad", {
            "replicas": 1, "size": 1, "runtime": "jax",
            "model": {"name": "m1"}, "servedModelName": "bad-served",
            "modelConfig": "tiny",
            "instanceSpec": {"volumes": [{"name": "models",
                                          "emptyDir": {}}]},
        }))
    wait_for(lambda: (api.get(GV, "arksapplications", "default", "bad")
                      .get("status", {}).get("phase")) == "Failed")
    conds = api.get(GV, "arksapplications", "default", "bad")["status"]["conditions"]
    pre = [c for c in conds if c["type"] == "Precheck"][0]
    assert pre["status"] == "False" and "reserved" in pre["message"]


def test_live_unified_disagg_unit_podgroup(live):
    """Unified layout in LIVE mode: every tier's pods join ONE unit-wide
    PodGroup whose minMember spans router + prefill + decode — not
    per-group PodGroups (reference generateUnifiedRBGS :1265-1326)."""
    api, op = live
    api.create(GV, "arksmodels", "default",
               _cr("ArksModel", "m1", {"model": "org/m"}))
    api.create(GV, "arksdisaggregatedapplications", "default", _cr(
        "ArksDisaggregatedApplication", "updd", {
            "runtime": "jax", "model": {"name": "m1"},
            "servedModelName": "u-served", "modelConfig": "tiny",
            "mode": "unified",
            "podGroupPolicy": {"kubeScheduling": {}},
            "prefill": {"replicas": 1, "accelerator": "tpu-v5p-16"},  # 2 hosts
            "decode": {"replicas": 1},
            "router": {"replicas": 1},
        }))
    pg = wait_for(lambda: api.get("scheduling.x-k8s.io/v1alpha1", "podgroups",
                                  "default", "arks-updd"))
    # 1 router + 1x2 prefill hosts + 1x1 decode host.
    assert pg["spec"]["minMember"] == 4
    # Tier pods carry the UNIT marker, and no per-group PodGroups exist.
    sts = api.get("apps/v1", "statefulsets", "default", "arks-updd-prefill-0")
    labels = sts["spec"]["template"]["metadata"]["labels"]
    assert labels["scheduling.x-k8s.io/pod-group"] == "arks-updd"
    for s in api.list("apps/v1", "statefulsets"):
        nm = s["metadata"]["name"]
        assert api.get("scheduling.x-k8s.io/v1alpha1", "podgroups",
                       "default", nm) is None


def test_live_unified_to_legacy_cleans_unit_podgroup(live):
    """Switching a live disaggregated app from unified back to legacy must
    delete the unit-wide PodGroup (its large minMember would otherwise
    haunt the scheduler forever)."""
    api, op = live
    api.create(GV, "arksmodels", "default",
               _cr("ArksModel", "m1", {"model": "org/m"}))
    api.create(GV, "arksdisaggregatedapplications", "default", _cr(
        "ArksDisaggregatedApplication", "sw", {
            "runtime": "jax", "model": {"name": "m1"},
            "servedModelName": "sw-served", "modelConfig": "tiny",
            "mode": "unified", "podGroupPolicy": {"kubeScheduling": {}},
            "prefill": {"replicas": 1}, "decode": {"replicas": 1},
            "router": {"replicas": 1},
        }))
    wait_for(lambda: api.get("scheduling.x-k8s.io/v1alpha1", "podgroups",
                             "default", "arks-sw"))
    api.patch(GV, "arksdisaggregatedapplications", "default", "sw",
              {"spec": {"mode": "legacy"}})
    wait_for(lambda: api.get("scheduling.x-k8s.io/v1alpha1", "podgroups",
                             "default", "arks-sw") is None)
    # Legacy per-group PodGroups take its place.
    wait_for(lambda: api.get("scheduling.x-k8s.io/v1alpha1", "podgroups",
                             "default", "arks-sw-prefill-0"))


def test_live_disagg_router_service_discovery(live):
    """Live-mode routers discover tier pods by label selector: the router
    gangset command carries --service-discovery, its pods bind the
    bootstrap ServiceAccount (Role/RoleBinding created like the reference's
    sglang-router RBAC), and tier pods carry the application/component
    labels the selector matches."""
    api, op = live
    api.create(GV, "arksmodels", "default",
               _cr("ArksModel", "m1", {"model": "org/m"}))
    api.create(GV, "arksdisaggregatedapplications", "default", _cr(
        "ArksDisaggregatedApplication", "sd1", {
            "runtime": "jax", "model": {"name": "m1"},
            "servedModelName": "sd-served", "modelConfig": "tiny",
            "prefill": {"replicas": 1}, "decode": {"replicas": 1},
            "router": {"replicas": 1},
        }))
    router_sts = wait_for(lambda: api.get(
        "apps/v1", "statefulsets", "default", "arks-sd1-router-0"))
    tmpl = router_sts["spec"]["template"]
    c = tmpl["spec"]["containers"][0]
    args = c.get("command", []) + c.get("args", [])
    assert "--service-discovery" in args
    assert "--application" in args and "sd1" in args
    assert "--discovery-file" not in args
    assert tmpl["spec"]["serviceAccountName"] == "arks-sd1-router"
    # RBAC bootstrap (reference :530-596).
    assert api.get("v1", "serviceaccounts", "default", "arks-sd1-router")
    role = api.get("rbac.authorization.k8s.io/v1", "roles", "default",
                   "arks-sd1-router")
    assert {"pods"} == set(role["rules"][0]["resources"])
    assert api.get("rbac.authorization.k8s.io/v1", "rolebindings",
                   "default", "arks-sd1-router")
    # Tier pods carry the labels KubeDiscovery selects on.
    for tier in ("prefill", "decode"):
        sts = api.get("apps/v1", "statefulsets", "default",
                      f"arks-sd1-{tier}-0")
        labels = sts["spec"]["template"]["metadata"]["labels"]
        assert labels["arks.ai/application"] == "sd1"
        assert labels["arks.ai/component"] == tier


def test_watch_driven_propagation_and_bounded_requests():
    """VERDICT (round-2 item 6): watch streams drive ingest — a CR change
    propagates in well under the resync interval, with a BOUNDED number of
    apiserver requests per change (no per-tick full relists)."""
    api = FakeKubeApi()
    # Long intervals: if propagation relied on polling/resync, this test
    # would time out; only the watch path can deliver the spec in time.
    op = LiveOperator(api, models_root="/tmp/watch-models", interval_s=0.2,
                      resync_interval_s=3600.0)
    op.start()
    try:
        assert op.use_watch
        time.sleep(0.5)  # initial resync done; watchers armed
        api.create(GV, "arksmodels", "default",
                   _cr("ArksModel", "wm1", {"model": "org/m",
                                            "source": None}))
        t0 = time.monotonic()
        wait_for(lambda: op.store.try_get(
            __import__("arks_tpu.control.resources",
                       fromlist=["Model"]).Model, "wm1"), timeout=5)
        assert time.monotonic() - t0 < 2.0  # event latency, not resync
        # Spec UPDATE also rides the watch.
        api.patch(GV, "arksmodels", "default", "wm1",
                  {"spec": {"model": "org/m2"}})
        wait_for(lambda: op.store.get(
            __import__("arks_tpu.control.resources",
                       fromlist=["Model"]).Model, "wm1")
            .spec.get("model") == "org/m2", timeout=5)

        # Bounded request count: between changes, the operator must not
        # hammer the apiserver with full relists.  Allow status writes and
        # the pending watch re-opens; assert LISTS stay flat.
        time.sleep(0.5)
        lists_before = sum(1 for v, _ in api.actions if v == "list")
        time.sleep(2.0)
        lists_after = sum(1 for v, _ in api.actions if v == "list")
        assert lists_after - lists_before <= 2, (
            f"{lists_after - lists_before} lists in 2s of idle watch mode")
    finally:
        op.stop()


def test_poll_mode_still_works_without_watch():
    """APIs without watch support (use_watch=False) keep the old polling
    behavior end to end."""
    api = FakeKubeApi()
    op = LiveOperator(api, models_root="/tmp/poll-models", interval_s=0.1,
                      use_watch=False)
    op.start()
    try:
        assert not op.use_watch
        api.create(GV, "arksmodels", "default",
                   _cr("ArksModel", "pm1", {"model": "org/m"}))
        from arks_tpu.control.resources import Model
        wait_for(lambda: op.store.try_get(Model, "pm1"), timeout=5)
    finally:
        op.stop()


# ---------------------------------------------------------------------------
# Leader election (reference cmd/main.go:198-216) + health endpoints
# ---------------------------------------------------------------------------


def _mk_op(api, tmp_path, ident, lease_s=30.0, retry_s=0.05):
    # Default lease is deliberately LONG: on a loaded CI box (e2e gang
    # subprocesses from earlier test files can linger through teardown) a
    # starved elector thread must not lose its lease mid-test (the expiry
    # test passes its own short duration).
    from arks_tpu.control.leader import LeaderElector
    elector = LeaderElector(api, namespace="arks-system", identity=ident,
                            lease_duration_s=lease_s, retry_period_s=retry_s)
    return LiveOperator(api, models_root=str(tmp_path / ident),
                        interval_s=0.1, leader_elector=elector,
                        exit_on_lost_lease=False)


def test_leader_election_single_writer(tmp_path):
    """TWO operators against one apiserver: exactly one acquires the Lease
    and reconciles; the standby ingests NOTHING and writes nothing."""
    api = FakeKubeApi()
    a = _mk_op(api, tmp_path, "op-a")
    b = _mk_op(api, tmp_path, "op-b")
    a.start()
    wait_for(lambda: a.is_leader)
    b.start()
    try:
        _mk_app(api, replicas=1)
        wait_for(lambda: _sts_names(api) == ["arks-app1-0"])
        # Sustained: the standby never became leader, never started its
        # machinery, and its store saw nothing.
        time.sleep(0.5)
        assert a.is_leader and not b.is_leader
        assert a._machinery_started and not b._machinery_started
        from arks_tpu.control import resources as res
        assert b.store.list(res.Application) == []
        lease = api.get("coordination.k8s.io/v1", "leases", "arks-system",
                        "e4ada7ad.arks.ai")
        assert lease["spec"]["holderIdentity"] == "op-a"
    finally:
        b.stop()
        a.stop()


def test_leader_failover_on_graceful_release(tmp_path):
    """Stopping the leader RELEASES the lease; the standby takes over at
    its next retry and reconciles new CRs."""
    api = FakeKubeApi()
    a = _mk_op(api, tmp_path, "op-a")
    b = _mk_op(api, tmp_path, "op-b")
    a.start()
    wait_for(lambda: a.is_leader)
    b.start()
    try:
        _mk_app(api, replicas=1)
        wait_for(lambda: _sts_names(api) == ["arks-app1-0"])
        a.stop()
        wait_for(lambda: b.is_leader)
        wait_for(lambda: b._machinery_started)
        # The new leader reconciles: a second app materializes.
        api.create(GV, "arksapplications", "default", _cr(
            "ArksApplication", "app2", {
                "replicas": 1, "size": 1, "runtime": "jax",
                "model": {"name": "m1"}, "servedModelName": "m2",
                "modelConfig": "tiny"}))
        wait_for(lambda: "arks-app2-0" in _sts_names(api))
    finally:
        b.stop()
        a.stop()


def test_leader_failover_on_lease_expiry(tmp_path):
    """A CRASHED leader (no release) is replaced once its lease expires —
    the takeover path a wedged holder exercises."""
    api = FakeKubeApi()
    # 2s lease: long enough that suite-load starvation cannot pre-expire
    # it before the crash is simulated, short enough to keep the test
    # quick.  No wall-clock lower bound on the takeover — under load the
    # lease may already be near expiry when the elector stops; the EXPIRY
    # path is evidenced by the holder change + leaseTransitions instead.
    a = _mk_op(api, tmp_path, "op-a", lease_s=2.0)
    b = _mk_op(api, tmp_path, "op-b", lease_s=2.0)
    a.start()
    wait_for(lambda: a.is_leader)
    b.start()
    try:
        assert not b.is_leader  # held and unexpired: no steal
        # Simulate a crash: the elector thread dies WITHOUT releasing.
        a.elector.stop(release=False)
        a._stop_machinery()
        from arks_tpu.control.leader import _parse_rfc3339
        dead = api.get("coordination.k8s.io/v1", "leases", "arks-system",
                       "e4ada7ad.arks.ai")["spec"]
        expiry = (_parse_rfc3339(dead["renewTime"])
                  + dead["leaseDurationSeconds"])
        wait_for(lambda: b.is_leader, timeout=30.0)
        lease = api.get("coordination.k8s.io/v1", "leases", "arks-system",
                        "e4ada7ad.arks.ai")
        assert lease["spec"]["holderIdentity"] == "op-b"
        assert lease["spec"]["leaseTransitions"] >= 1
        # EXPIRY-gated, proven from the Lease's own timestamps (immune to
        # host scheduling noise): the takeover happened after the dead
        # leader's lease ran out, not as a steal of a live one.
        assert _parse_rfc3339(lease["spec"]["acquireTime"]) >= expiry
    finally:
        b.stop()
        a.stop()


def test_health_endpoints(tmp_path):
    """/healthz + /readyz over HTTP: leader live+ready; standby live but
    NOT ready (readiness gates the embedded gateway's Service endpoints to
    the leader — a standby's gateway would serve an empty store)."""
    import json
    import urllib.request

    from arks_tpu.control.live import HealthServer

    api = FakeKubeApi()
    a = _mk_op(api, tmp_path, "op-a")
    b = _mk_op(api, tmp_path, "op-b")
    ha = HealthServer(a, host="127.0.0.1", port=0)
    hb = HealthServer(b, host="127.0.0.1", port=0)
    ha.start()
    hb.start()
    a.start()
    wait_for(lambda: a.is_leader)
    b.start()
    try:
        import urllib.error

        def hit(port, path):
            try:
                r = urllib.request.urlopen(
                    f"http://127.0.0.1:{port}{path}", timeout=5)
                return r.status, json.loads(r.read())
            except urllib.error.HTTPError as e:
                return e.code, json.loads(e.read())

        for path in ("/healthz", "/readyz"):
            code, body = hit(ha.port, path)
            assert code == 200 and body["leader"] is True
        # Standby: live (healthz 200) but NOT ready (readyz 503) — the
        # gateway Service must route to the leader only.
        code, body = hit(hb.port, "/healthz")
        assert code == 200 and body["leader"] is False
        code, body = hit(hb.port, "/readyz")
        assert code == 503 and body["ok"] is False
        assert body["identity"] == "op-b"
        # Unknown path -> 404.
        import urllib.error
        try:
            urllib.request.urlopen(f"http://127.0.0.1:{ha.port}/nope",
                                   timeout=5)
            assert False, "expected 404"
        except urllib.error.HTTPError as e:
            assert e.code == 404
    finally:
        hb.stop()
        ha.stop()
        b.stop()
        a.stop()


def test_metrics_endpoint_tokenreview_authenticated(tmp_path):
    """Operator /metrics: 401 without a bearer token, 403 on an invalid
    one, 200 + operator families for a TokenReview-valid token (the
    reference manager's authenticated metrics filter)."""
    import urllib.error
    import urllib.request

    from arks_tpu.control.live import HealthServer

    api = FakeKubeApi()
    api.valid_tokens.add("sa-prom-token")
    op = LiveOperator(api, models_root=str(tmp_path / "m"), interval_s=0.1)
    hs = HealthServer(op, host="127.0.0.1", port=0, metrics_auth_api=api)
    hs.start()
    op.start()
    try:
        _mk_app(api, replicas=1)
        wait_for(lambda: _sts_names(api) == ["arks-app1-0"])

        def hit(token=None):
            req = urllib.request.Request(
                f"http://127.0.0.1:{hs.port}/metrics",
                headers={"Authorization": f"Bearer {token}"} if token else {})
            try:
                r = urllib.request.urlopen(req, timeout=5)
                return r.status, r.read().decode()
            except urllib.error.HTTPError as e:
                return e.code, ""

        assert hit()[0] == 401
        assert hit("wrong-token")[0] == 403
        code, text = hit("sa-prom-token")
        assert code == 200
        assert "operator_sync_iterations_total" in text
        assert "operator_spec_ingests_total" in text
        assert 'operator_watch_events_total{' in text
        assert "operator_is_leader" in text

        # Probes stay unauthenticated (kubelet has no bearer token here).
        r = urllib.request.urlopen(
            f"http://127.0.0.1:{hs.port}/healthz", timeout=5)
        assert r.status == 200
    finally:
        hs.stop()
        op.stop()


def test_token_review_over_http_apiserver():
    """KubeApi.token_review round-trips the TokenReview POST against the
    fake apiserver (the in-cluster call path)."""
    from arks_tpu.control.k8s_client import FakeApiServer, KubeApi

    srv = FakeApiServer()
    srv.start()
    try:
        srv.fake.valid_tokens.add("good")
        api = KubeApi(srv.url)
        assert api.token_review("good") is True
        assert api.token_review("bad") is False
    finally:
        srv.stop()
