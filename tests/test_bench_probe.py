"""bench.py backend-probe persistence: an initially-unreachable backend
must be retried for the whole probe window (capped exponential backoff),
and the bench must still run to completion once the backend comes up —
three driver rounds recorded 0.0 because the old 3x180s loop gave up
before the tunnel returned."""

import json
import os
import subprocess
import sys
import time

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import bench  # noqa: E402


def _flaky_code(tmp_path, fail_times: int) -> str:
    """Probe snippet that fails ``fail_times`` runs, then succeeds —
    simulates a tunnel that comes back mid-window."""
    marker = tmp_path / "probe_attempts"
    return (
        "import pathlib, sys\n"
        f"p = pathlib.Path({str(marker)!r})\n"
        "n = int(p.read_text()) if p.exists() else 0\n"
        "p.write_text(str(n + 1))\n"
        f"sys.exit(0 if n >= {fail_times} else 1)\n")


def test_probe_deadline_mode_retries_until_backend_returns(tmp_path):
    t0 = time.monotonic()
    ok, err = bench.probe_backend(
        timeout_s=30.0, deadline_s=60.0, backoff_s=0.05, max_backoff_s=0.2,
        code=_flaky_code(tmp_path, fail_times=3))
    assert ok, err
    assert time.monotonic() - t0 < 30.0  # succeeded well inside the window


def test_probe_deadline_mode_gives_up_at_deadline(tmp_path):
    t0 = time.monotonic()
    ok, err = bench.probe_backend(
        timeout_s=30.0, deadline_s=1.0, backoff_s=0.2, max_backoff_s=0.4,
        code="import sys; sys.exit(1)")
    assert not ok and err
    assert time.monotonic() - t0 < 10.0  # bounded by the deadline, not 3x180


def test_probe_legacy_attempts_mode_still_bounded(tmp_path):
    ok, _ = bench.probe_backend(
        timeout_s=30.0, attempts=2, backoff_s=0.05,
        code="import sys; sys.exit(1)")
    assert not ok


@pytest.mark.slow
def test_bench_runs_to_completion_with_initially_unreachable_backend(
        tmp_path):
    """Full bench.py subprocess: the probe fails twice (simulated outage),
    then succeeds; the run must complete and emit the result JSON including
    the mixed_step_ttft_under_load_ms metric line."""
    env = dict(os.environ)
    env.update({
        "JAX_PLATFORMS": "cpu",
        "ARKS_BENCH_MODEL": "tiny",
        "ARKS_BENCH_BATCH": "2",
        "ARKS_BENCH_CACHE_LEN": "64",
        "ARKS_BENCH_STEPS": "4",
        "ARKS_BENCH_TRIALS": "1",
        "ARKS_BENCH_PROMPT_LEN": "32",
        "ARKS_BENCH_TTFT_TRIALS": "2",
        "ARKS_BENCH_KV_DTYPE": "bf16",
        "ARKS_BENCH_WEIGHT_DTYPE": "bf16",
        "ARKS_BENCH_SERVING": "0",
        "ARKS_BENCH_MIXED_TRIALS": "2",
        "ARKS_BENCH_PROBE_DEADLINE_S": "120",
        "ARKS_BENCH_PROBE_BACKOFF": "0.1",
        "ARKS_BENCH_PROBE_CODE": _flaky_code(tmp_path, fail_times=2),
    })
    r = subprocess.run([sys.executable, "bench.py"], cwd=REPO, env=env,
                       capture_output=True, text=True, timeout=600)
    assert r.returncode == 0, r.stderr[-2000:]
    last = [l for l in r.stdout.strip().splitlines() if l.startswith("{")][-1]
    result = json.loads(last)
    assert "error" not in result, result
    assert result["value"] > 0
    assert result["probe_wait_s"] > 0
    assert "mixed_step_ttft_under_load_ms" in result, result
    assert result["mixed_step_ttft_under_load_ms"] > 0
