"""Full-stack e2e on one host: operator + REAL engine subprocess + gateway.

The "minimum end-to-end slice" (SURVEY.md §7 stage 4) plus the gateway:
manifests -> controllers -> LocalProcessDriver spawns a real
``python -m arks_tpu.server`` process -> Endpoint discovers it -> client
calls the gateway with a token and gets an engine-generated completion with
metered usage.
"""

import json
import time
import urllib.request

import pytest

from arks_tpu.control import resources as res
from arks_tpu.control.manager import build_manager
from arks_tpu.control.workloads import LocalProcessDriver
from arks_tpu.gateway.server import Gateway


@pytest.fixture(scope="module")
def stack(tmp_path_factory):
    root = tmp_path_factory.mktemp("e2e")
    driver = LocalProcessDriver(log_dir=str(root / "logs"))
    mgr = build_manager(models_root=str(root / "models"), driver=driver,
                        local_platform="cpu")
    mgr.start()
    gw = Gateway(mgr.store, host="127.0.0.1", port=0, quota_sync_s=0.5)
    gw.start(background=True)
    yield mgr, gw, driver
    gw.stop()
    mgr.stop()
    # Tear down spawned engines.
    for gs in mgr.store.list(res.GangSet):
        driver.teardown(gs)


def wait_for(predicate, timeout=120.0, interval=0.25):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        v = predicate()
        if v:
            return v
        time.sleep(interval)
    raise AssertionError("condition not met within timeout")


def test_quickstart_end_to_end(stack):
    mgr, gw, _driver = stack
    store = mgr.store

    store.create(res.Model(name="tiny-model", spec={"model": "test/tiny"}))
    store.create(res.Application(name="tiny-app", spec={
        "replicas": 1, "size": 1, "runtime": "jax",
        "model": {"name": "tiny-model"},
        "servedModelName": "tiny-served",
        "tensorParallel": 1,
        "modelConfig": "tiny",
        "runtimeCommonArgs": ["--num-slots", "2", "--max-model-len", "64"],
    }))
    store.create(res.Endpoint(name="tiny-served", spec={"defaultWeight": 1}))
    store.create(res.Token(name="e2e-user", spec={
        "token": "sk-e2e",
        "qos": [{"endpoint": {"name": "tiny-served"},
                 "rateLimits": [{"type": "rpm", "value": 50}],
                 "quota": {"name": "e2e-quota"}}]}))
    store.create(res.Quota(name="e2e-quota", spec={
        "quotas": [{"type": "total", "value": 100000}]}))

    # Engine subprocess boot: jax import + compile, tens of seconds on CPU.
    wait_for(lambda: store.get(res.Application, "tiny-app").status.get("phase")
             == res.PHASE_RUNNING, timeout=180)
    ep = wait_for(lambda: (store.get(res.Endpoint, "tiny-served").status.get("routes")
                           or None), timeout=30)
    assert ep[0]["backend"]["addresses"]

    req = urllib.request.Request(
        f"http://127.0.0.1:{gw.port}/v1/chat/completions",
        data=json.dumps({
            "model": "tiny-served",
            "messages": [{"role": "user", "content": "hello"}],
            "max_tokens": 5, "temperature": 0, "ignore_eos": True,
        }).encode(),
        headers={"Content-Type": "application/json",
                 "Authorization": "Bearer sk-e2e"})
    with urllib.request.urlopen(req, timeout=120) as r:
        data = json.load(r)
    assert data["object"] == "chat.completion"
    assert data["usage"]["completion_tokens"] == 5
    assert data["choices"][0]["finish_reason"] == "length"

    # Usage metered through the gateway into the quota service.
    total = data["usage"]["total_tokens"]
    assert gw.quota.get_usage("default", "e2e-quota")["total"] == total

    # Streamed request through the whole stack.
    req = urllib.request.Request(
        f"http://127.0.0.1:{gw.port}/v1/chat/completions",
        data=json.dumps({
            "model": "tiny-served",
            "messages": [{"role": "user", "content": "again"}],
            "max_tokens": 4, "temperature": 0, "ignore_eos": True,
            "stream": True, "stream_options": {"include_usage": True},
        }).encode(),
        headers={"Content-Type": "application/json",
                 "Authorization": "Bearer sk-e2e"})
    frames = []
    with urllib.request.urlopen(req, timeout=120) as r:
        for raw in r:
            line = raw.decode().strip()
            if line.startswith("data: "):
                frames.append(line[6:])
    assert frames[-1] == "[DONE]"
    wait_for(lambda: gw.quota.get_usage("default", "e2e-quota")["total"] > total,
             timeout=10)


def _launch_gang(store, name, served, extra_args=()):
    """Shared size-2 gang scaffolding: create the app + endpoint, wait for
    Running, return the leader address."""
    if store.try_get(res.Model, "gang-model") is None:
        store.create(res.Model(name="gang-model", spec={"model": "test/tiny"}))
    store.create(res.Application(name=name, spec={
        "replicas": 1, "size": 2, "runtime": "jax",
        "model": {"name": "gang-model"},
        "servedModelName": served,
        "tensorParallel": 2,
        "modelConfig": "tiny",
        "runtimeCommonArgs": ["--num-slots", "2", "--max-model-len", "64",
                              *extra_args],
    }))
    store.create(res.Endpoint(name=served, spec={"defaultWeight": 1}))
    # Two engine processes boot + distributed rendezvous + compile.
    wait_for(lambda: store.get(res.Application, name).status.get("phase")
             == res.PHASE_RUNNING, timeout=240)
    ep = wait_for(lambda: (store.get(res.Endpoint, served).status.get("routes")
                           or None), timeout=30)
    return ep[0]["backend"]["addresses"][0]


def _complete(addr, served, prompt, max_tokens):
    req = urllib.request.Request(
        f"http://{addr}/v1/completions",
        data=json.dumps({
            "model": served, "prompt": prompt,
            "max_tokens": max_tokens, "temperature": 0, "ignore_eos": True,
        }).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=120) as r:
        return json.load(r)


def _assert_gang_alive(store, driver, name, members=2):
    time.sleep(2)
    gs = store.get(res.GangSet, name)
    group = driver._groups[gs.key][0]
    assert len(group.procs) == members
    assert all(p.poll() is None for p in group.procs)
    assert gs.status["readyReplicas"] == 1


def test_multiprocess_gang_serves(stack):
    """VERDICT acceptance: a size-2 gang launches BOTH members as real
    processes, they rendezvous via jax.distributed (gloo collectives over
    the 2-process CPU mesh), the leader broadcasts every dispatch to the
    follower, and the gang serves a real completion with tp=2 sharding
    spanning both processes."""
    mgr, gw, driver = stack
    store = mgr.store
    addr = _launch_gang(store, "gang-app", "gang-served")

    data = _complete(addr, "gang-served", "multi host", 6)
    assert data["usage"]["completion_tokens"] == 6
    assert data["choices"][0]["finish_reason"] == "length"

    # A second request exercises steady-state decode through the follower.
    data2 = _complete(addr, "gang-served", "again please", 4)
    assert data2["usage"]["completion_tokens"] == 4

    # The gang is really 2 live processes (leader + follower) and the
    # follower SURVIVES serving (a desync/crash there would show up as a
    # dead member and a group restart).
    _assert_gang_alive(store, driver, "gang-app")


def test_multiprocess_gang_with_spec_decode(stack):
    """A size-2 gang serving WITH speculative decoding: the leader
    broadcasts draft-prefill and spec dispatches, the follower mirrors
    them, and greedy output stays correct across the gang."""
    mgr, gw, driver = stack
    store = mgr.store
    addr = _launch_gang(store, "spec-gang", "spec-gang-served",
                        extra_args=["--draft-model", "tiny-gqa",
                                    "--draft-len", "4",
                                    "--prefix-cache-mb", "0"])

    data = _complete(addr, "spec-gang-served", "multi host spec", 6)
    assert data["usage"]["completion_tokens"] == 6

    # The spec path really fired on the gang (not a silent fused fallback).
    metrics = urllib.request.urlopen(f"http://{addr}/metrics",
                                     timeout=10).read().decode()
    prop = [l for l in metrics.splitlines()
            if l.startswith("spec_decode_proposed_tokens_total")]
    assert prop and float(prop[0].split()[-1]) > 0

    # Both processes alive after speculative serving.
    _assert_gang_alive(store, driver, "spec-gang")


def test_gang_member_death_restarts_group_and_serving_recovers(stack):
    """Failure detection e2e: killing a gang FOLLOWER mid-serving must take
    the whole group down (shared fate — the leader exits when its dispatch
    channel breaks rather than silently diverging), the driver restarts the
    gang, and serving recovers on the fresh processes.

    Reuses the gang from test_multiprocess_gang_serves (same module-scoped
    stack, runs after it in file order)."""
    mgr, gw, driver = stack
    store = mgr.store
    gs = store.get(res.GangSet, "gang-app")
    group = driver._groups[gs.key][0]
    old_procs = list(group.procs)
    assert all(p.poll() is None for p in old_procs)

    old_procs[1].kill()  # the follower

    # Shared fate + restart: eventually a NEW set of live processes.
    def regrouped():
        g = driver._groups.get(gs.key, [None])[0]
        if g is None or g.procs is old_procs:
            return False
        return (len(g.procs) == 2
                and all(p.poll() is None for p in g.procs)
                and all(p.pid != q.pid for p, q in zip(g.procs, old_procs)))
    wait_for(regrouped, timeout=60)

    # Readiness dips then recovers; the fresh gang serves.  The status and
    # route lag the restart (and the relaunch may bind a new port), so poll
    # the completion against the CURRENT route until it lands.
    def served_again():
        try:
            routes = store.get(res.Endpoint, "gang-served").status["routes"]
            if not routes or not routes[0]["backend"]["addresses"]:
                return False
            addr = routes[0]["backend"]["addresses"][0]
            data = _complete(addr, "gang-served", "after the restart", 4)
            return data["usage"]["completion_tokens"] == 4
        except Exception:
            return False

    wait_for(served_again, timeout=240, interval=2.0)


def test_follower_wedge_unreadies_gang_then_restarts(stack, monkeypatch):
    """Worker-wedge failure injection: SIGSTOP a gang FOLLOWER (alive but
    hung — the case member-death detection cannot see).  The follower's
    dispatch-channel heartbeat goes stale, the leader's /readiness flips
    503 within the bounded window (gang out of Service endpoints), and
    past the fatal deadline the leader exits so the driver restarts the
    whole group (the LWS RecreateGroupOnPodRestart behavior, extended to
    hangs)."""
    import os as _os
    import signal as _signal
    import urllib.error

    mgr, gw, driver = stack
    store = mgr.store
    # Env is inherited by the spawned gang processes (driver launches with
    # this process's environ): tight heartbeat/stale/fatal windows.
    monkeypatch.setenv("ARKS_GANG_HB_INTERVAL", "0.3")
    monkeypatch.setenv("ARKS_GANG_STALE_S", "2")
    monkeypatch.setenv("ARKS_GANG_WEDGE_FATAL_S", "10")
    addr = _launch_gang(store, "wedge-gang", "wedge-served")
    assert _complete(addr, "wedge-served", "pre-wedge", 4)[
        "usage"]["completion_tokens"] == 4

    gs = store.get(res.GangSet, "wedge-gang")
    group = driver._groups[gs.key][0]
    old_procs = list(group.procs)
    follower = old_procs[1]
    _os.kill(follower.pid, _signal.SIGSTOP)
    try:
        # Readiness flips within the stale window — the worker is alive
        # (not reaped) yet the gang must leave Service endpoints.
        def unready():
            assert follower.poll() is None  # still "alive" (stopped)
            try:
                urllib.request.urlopen(f"http://{addr}/readiness",
                                       timeout=5)
                return False
            except urllib.error.HTTPError as e:
                return e.code == 503 and b"heartbeat" in e.read()
            except Exception:
                return False
        wait_for(unready, timeout=30)

        # Escalation: leader exits past the fatal deadline, the driver
        # restarts the WHOLE group with fresh processes.
        def regrouped():
            g = driver._groups.get(gs.key, {}).get(0)
            if g is None or g.procs is old_procs:
                return False
            return (len(g.procs) == 2
                    and all(p.poll() is None for p in g.procs)
                    and all(p.pid != q.pid
                            for p, q in zip(g.procs, old_procs)))
        wait_for(regrouped, timeout=120)
    finally:
        if follower.poll() is None:
            _os.kill(follower.pid, _signal.SIGCONT)


def test_counter_store_outage_fails_cleanly():
    """A dead shared counter store (Redis down) must fail requests quickly
    and cleanly — bounded by the client's socket timeout — not hang the
    gateway's handler threads."""
    import urllib.error

    from arks_tpu.control.store import Store
    from arks_tpu.gateway.ratelimiter import RateLimiter
    from arks_tpu.gateway.rediskv import (
        RedisCounterBackend, RespClient, RespServer)
    from arks_tpu.gateway.server import Gateway

    # A live counter store at startup (RespClient fails fast on a bad
    # address by design) that dies mid-flight.
    resp = RespServer(host="127.0.0.1", port=0)
    resp.start(background=True)

    store = Store()
    store.create(res.Endpoint(name="m1", namespace="default", spec={},
                              status={"routes": []}))
    store.create(res.Token(name="t", namespace="default", spec={
        "token": "sk-t", "qos": [{"endpoint": {"name": "m1"}}]}))
    gw = Gateway(store, host="127.0.0.1", port=0,
                 rate_limiter=RateLimiter(RedisCounterBackend(
                     RespClient("127.0.0.1", resp.port, timeout_s=0.5))))
    gw.start(background=True)
    resp.stop()  # the outage
    try:
        wait_for(lambda: gw.qos.token_known("sk-t"), timeout=10)
        req = urllib.request.Request(
            f"http://127.0.0.1:{gw.port}/v1/chat/completions",
            data=json.dumps({"model": "m1", "messages": []}).encode(),
            headers={"Content-Type": "application/json",
                     "Authorization": "Bearer sk-t"})
        t0 = time.monotonic()
        try:
            urllib.request.urlopen(req, timeout=30)
            raise AssertionError("expected an error response")
        except urllib.error.HTTPError as e:
            assert e.code >= 500  # clean server error, not a hang
        assert time.monotonic() - t0 < 10  # bounded by the socket timeout
    finally:
        gw.stop()
