"""Trainer checkpoint/resume (train/checkpoint.py): a resumed run must be
bit-identical to an uninterrupted one, restores must land sharded on the
mesh, and retention must bound the step directory."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from arks_tpu.models import get_config
from arks_tpu.parallel.mesh import make_mesh
from arks_tpu.train.checkpoint import (
    make_manager, restore_train_state, save_train_state)
from arks_tpu.train.sft import make_train_step, train_init


def _data(cfg, n_steps, batch=8, t=16):
    key = jax.random.PRNGKey(9)
    toks = jax.random.randint(key, (n_steps, batch, t), 2, cfg.vocab_size)
    mask = jnp.ones((batch, t), jnp.float32)
    return toks, mask


@pytest.mark.parametrize("use_mesh", [False, True])
def test_resume_matches_uninterrupted(tmp_path, use_mesh):
    cfg = get_config("tiny-gqa")
    optimizer = optax.adamw(1e-3)
    mesh = make_mesh(tensor_parallel=2, data_parallel=2,
                     devices=jax.devices()[:4]) if use_mesh else None
    toks, mask = _data(cfg, 4)
    step_fn = make_train_step(cfg, optimizer, mesh)

    # Uninterrupted: 4 steps straight through.
    state = train_init(cfg, jax.random.PRNGKey(1), optimizer, mesh)
    ref_losses = []
    for i in range(4):
        state, loss = step_fn(state, toks[i], toks[i], mask)
        ref_losses.append(float(loss))

    # Interrupted: 2 steps, save, restore into a FRESH manager, 2 more.
    state = train_init(cfg, jax.random.PRNGKey(1), optimizer, mesh)
    for i in range(2):
        state, loss = step_fn(state, toks[i], toks[i], mask)
        assert float(loss) == pytest.approx(ref_losses[i], rel=1e-6)
    mgr = make_manager(str(tmp_path / "ckpt"))
    assert save_train_state(mgr, state) == 2

    mgr2 = make_manager(str(tmp_path / "ckpt"))
    resumed = restore_train_state(mgr2, cfg, optimizer, mesh)
    assert int(resumed.step) == 2
    # BIT-identical restore: the module's whole guarantee.
    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a),
                                                   np.asarray(b)),
        state, resumed)
    if use_mesh:
        # Restored leaves land SHARDED on the mesh, not replicated host
        # arrays (each host reads only its shards on real multi-host) —
        # optimizer moments included.
        wq = resumed.params["layers"]["wq"]
        assert wq.sharding.mesh.shape == mesh.shape
        mu_wq = resumed.opt_state[0].mu["layers"]["wq"]
        assert mu_wq.sharding == wq.sharding
    for i in (2, 3):
        resumed, loss = step_fn(resumed, toks[i], toks[i], mask)
        assert float(loss) == ref_losses[i]  # exact, not approx


def test_restore_honors_stored_dtype(tmp_path):
    """A bf16 run restores bf16 WITHOUT the caller restating the dtype —
    the template dtype comes from the checkpoint's own metadata (a silent
    f32 cast would break bit-identical resume and double param memory)."""
    cfg = get_config("tiny")
    optimizer = optax.sgd(1e-2)
    state = train_init(cfg, jax.random.PRNGKey(0), optimizer,
                       dtype=jnp.bfloat16)
    mgr = make_manager(str(tmp_path / "bf"))
    save_train_state(mgr, state)
    restored = restore_train_state(make_manager(str(tmp_path / "bf")),
                                   cfg, optimizer)
    assert restored.params["embed"].dtype == jnp.bfloat16
    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a),
                                                   np.asarray(b)),
        state, restored)


def test_retention_and_latest(tmp_path):
    cfg = get_config("tiny")
    optimizer = optax.sgd(1e-2)
    toks, mask = _data(cfg, 5, batch=2, t=8)
    step_fn = make_train_step(cfg, optimizer, None)
    state = train_init(cfg, jax.random.PRNGKey(0), optimizer)
    mgr = make_manager(str(tmp_path / "c"), max_to_keep=2)
    for i in range(4):
        state, _ = step_fn(state, toks[i], toks[i], mask)
        save_train_state(mgr, state)
    assert mgr.latest_step() == 4
    assert sorted(mgr.all_steps()) == [3, 4]  # max_to_keep pruned the rest
    restored = restore_train_state(mgr, cfg, optimizer, step=3)
    assert int(restored.step) == 3
    with pytest.raises(FileNotFoundError):
        restore_train_state(make_manager(str(tmp_path / "empty")),
                            cfg, optimizer)
