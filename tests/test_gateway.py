"""Gateway data-plane tests: auth, QoS, rate limits, quota, routing, SSE
usage extraction — the behaviors of the reference's ext_proc plugin
(pkg/gateway), asserted over a stub OpenAI backend."""

import json
import threading
import time
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from arks_tpu.control import resources as res
from arks_tpu.control.store import Store
from arks_tpu.gateway.server import Gateway

PROMPT_TOKENS, COMPLETION_TOKENS = 7, 5


class _StubBackend:
    """Minimal OpenAI-compatible backend with fixed usage numbers."""

    def __init__(self, fail_with: int | None = None):
        self.requests: list[dict] = []
        stub = self

        class H(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):
                pass

            def do_POST(self):
                length = int(self.headers.get("Content-Length", 0))
                body = json.loads(self.rfile.read(length))
                stub.requests.append(
                    {"body": body,
                     "headers": {k.lower(): v for k, v in self.headers.items()}})
                if stub.fail_with:
                    self.send_response(stub.fail_with)
                    self.send_header("Content-Length", "0")
                    self.end_headers()
                    return
                usage = {"prompt_tokens": PROMPT_TOKENS,
                         "completion_tokens": COMPLETION_TOKENS,
                         "total_tokens": PROMPT_TOKENS + COMPLETION_TOKENS}
                if body.get("stream"):
                    self.send_response(200)
                    self.send_header("Content-Type", "text/event-stream")
                    frames = [
                        {"id": "x", "choices": [{"delta": {"content": "hi"}}]},
                        {"id": "x", "choices": [], "usage": usage},
                    ]
                    payload = b"".join(
                        b"data: " + json.dumps(f).encode() + b"\n\n" for f in frames
                    ) + b"data: [DONE]\n\n"
                    self.send_header("Content-Length", str(len(payload)))
                    self.end_headers()
                    self.wfile.write(payload)
                else:
                    data = json.dumps({"id": "x", "choices": [
                        {"message": {"content": "hello"}}], "usage": usage}).encode()
                    self.send_response(200)
                    self.send_header("Content-Type", "application/json")
                    self.send_header("Content-Length", str(len(data)))
                    self.end_headers()
                    self.wfile.write(data)

        self.fail_with = fail_with
        self.httpd = ThreadingHTTPServer(("127.0.0.1", 0), H)
        self.port = self.httpd.server_port
        threading.Thread(target=self.httpd.serve_forever, daemon=True).start()

    @property
    def addr(self):
        return f"127.0.0.1:{self.port}"

    def stop(self):
        self.httpd.shutdown()


@pytest.fixture()
def world():
    store = Store()
    backend = _StubBackend()
    store.create(res.Endpoint(name="m1", namespace="team-a", spec={}, status={
        "routes": [{"backend": {"addresses": [backend.addr]}, "weight": 1}]}))
    store.create(res.Token(name="alice", namespace="team-a", spec={
        "token": "sk-alice",
        "qos": [{"endpoint": {"name": "m1"},
                 "rateLimits": [{"type": "rpm", "value": 4}],
                 "quota": {"name": "alice-quota"}}]}))
    store.create(res.Quota(name="alice-quota", namespace="team-a", spec={
        "quotas": [{"type": "total", "value": 60}]}))
    gw = Gateway(store, host="127.0.0.1", port=0, quota_sync_s=0.2)
    gw.start(background=True)
    deadline = time.monotonic() + 10
    while not gw.qos.token_known("sk-alice") and time.monotonic() < deadline:
        time.sleep(0.02)  # wait for the token index pump
    yield gw, store, backend
    gw.stop()
    backend.stop()


def _post(gw, body, token="sk-alice", path="/v1/chat/completions"):
    req = urllib.request.Request(
        f"http://127.0.0.1:{gw.port}{path}",
        data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json",
                 **({"Authorization": f"Bearer {token}"} if token else {})})
    return urllib.request.urlopen(req, timeout=30)


def _err(fn):
    try:
        fn()
        raise AssertionError("expected HTTPError")
    except urllib.error.HTTPError as e:
        return e.code, json.load(e)


def test_auth_required(world):
    gw, _, _ = world
    code, body = _err(lambda: _post(gw, {"model": "m1"}, token=None))
    assert code == 401 and "Authorization" in body["error"]["message"]


def test_unknown_token_401(world):
    gw, _, _ = world
    code, _ = _err(lambda: _post(gw, {"model": "m1"}, token="sk-mallory"))
    assert code == 401


def test_model_not_in_qos_403(world):
    gw, store, _ = world
    store.create(res.Endpoint(name="m2", namespace="team-a", spec={}))
    code, _ = _err(lambda: _post(gw, {"model": "m2"}))
    assert code == 403


def test_unknown_model_404(world):
    gw, store, _ = world
    t = store.get(res.Token, "alice", "team-a")
    t.spec["qos"].append({"endpoint": {"name": "ghost"}, "rateLimits": []})
    store.update(t)
    time.sleep(0.2)
    code, _ = _err(lambda: _post(gw, {"model": "ghost"}))
    assert code == 404


def test_stream_requires_include_usage(world):
    gw, _, _ = world
    code, body = _err(lambda: _post(gw, {"model": "m1", "stream": True}))
    assert code == 400 and "include_usage" in body["error"]["message"]


def test_proxy_non_stream_and_usage_accounting(world):
    gw, store, backend = world
    with _post(gw, {"model": "m1", "messages": []}) as r:
        data = json.load(r)
    assert data["usage"]["total_tokens"] == 12
    # Routing headers injected toward the backend.
    hdrs = backend.requests[-1]["headers"]
    assert hdrs["x-arks-model"] == "m1"
    assert hdrs["x-arks-namespace"] == "team-a"
    assert hdrs["x-arks-username"] == "alice"
    # Quota accounted + persisted into the CR status by the syncer.
    assert gw.quota.get_usage("team-a", "alice-quota")["total"] == 12
    deadline = time.monotonic() + 5
    while time.monotonic() < deadline:
        q = store.get(res.Quota, "alice-quota", "team-a")
        used = {s["type"]: s["used"] for s in q.status.get("quotaStatus", [])}
        if used.get("total") == 12:
            break
        time.sleep(0.05)
    else:
        raise AssertionError("quota status not synced")


def test_streaming_relay_and_usage(world):
    gw, _, _ = world
    frames = []
    with _post(gw, {"model": "m1", "stream": True,
                    "stream_options": {"include_usage": True}}) as r:
        for raw in r:
            line = raw.decode().strip()
            if line.startswith("data: "):
                frames.append(line[6:])
    assert frames[-1] == "[DONE]"
    assert gw.quota.get_usage("team-a", "alice-quota")["total"] == 12


def test_rpm_limit_429(world):
    gw, _, _ = world
    for _ in range(4):
        _post(gw, {"model": "m1"}).read()
    code, body = _err(lambda: _post(gw, {"model": "m1"}))
    assert code == 429 and "rpm" in body["error"]["message"]


def test_quota_exhaustion_429(world):
    gw, store, _ = world
    t = store.get(res.Token, "alice", "team-a")
    t.spec["qos"][0]["rateLimits"] = [{"type": "rpm", "value": 100}]
    store.update(t)
    time.sleep(0.3)  # token index pump
    for _ in range(5):  # 5 * 12 = 60 >= limit 60
        _post(gw, {"model": "m1"}).read()
    code, body = _err(lambda: _post(gw, {"model": "m1"}))
    assert code == 429 and "quota" in body["error"]["message"]


def test_models_list_scoped_to_token(world):
    gw, _, _ = world
    req = urllib.request.Request(
        f"http://127.0.0.1:{gw.port}/v1/models",
        headers={"Authorization": "Bearer sk-alice"})
    with urllib.request.urlopen(req, timeout=10) as r:
        data = json.load(r)
    assert [m["id"] for m in data["data"]] == ["m1"]


def test_backend_failover(world):
    gw, store, backend = world
    ep = store.get(res.Endpoint, "m1", "team-a")
    # Dead backend first; gateway must fail over to the live one.
    ep.status["routes"] = [
        {"backend": {"addresses": ["127.0.0.1:1", backend.addr]}, "weight": 1}]
    store.update_status(ep)
    ok = 0
    for _ in range(4):
        with _post(gw, {"model": "m1"}) as r:
            ok += r.status == 200
    assert ok == 4


def test_restart_recovery_reseeds_from_cr(world):
    gw, store, backend = world
    _post(gw, {"model": "m1"}).read()
    deadline = time.monotonic() + 5
    while time.monotonic() < deadline:
        q = store.get(res.Quota, "alice-quota", "team-a")
        if q.status.get("quotaStatus"):
            break
        time.sleep(0.05)
    # Simulate a gateway restart: fresh QuotaService, empty counters.
    gw.quota._usage.clear()
    gw.syncer.sync_once()
    assert gw.quota.get_usage("team-a", "alice-quota")["total"] == 12


def test_no_backends_503(world):
    gw, store, _ = world
    ep = store.get(res.Endpoint, "m1", "team-a")
    ep.status["routes"] = []
    store.update_status(ep)
    code, _ = _err(lambda: _post(gw, {"model": "m1"}))
    assert code == 503


def test_oversize_body_413(world):
    """Client-buffer parity (dist/gateway.yaml:250-261): bodies beyond the
    cap are rejected up front, before buffering."""
    gw, _, _ = world
    gw.max_body_bytes = 1024
    big = {"model": "m1", "messages": [{"role": "user", "content": "x" * 4096}]}
    code, body = _err(lambda: _post(gw, big))
    assert code == 413
    assert "exceeds" in body["error"]["message"]


def test_processing_deadline_504(world):
    """Per-stage timeout (ext_proc messageTimeout parity): a wedged counter
    backend turns into a clean 504, not a hung connection."""
    gw, _, _ = world

    class SlowLimiter:
        def check_limit(self, *a, **k):
            time.sleep(0.2)
            return []

        def do_limit(self, *a, **k):
            return None

    gw.limiter = SlowLimiter()
    gw.process_timeout_s = 0.05
    code, body = _err(lambda: _post(
        gw, {"model": "m1", "messages": [{"role": "user", "content": "hi"}]}))
    assert code == 504
    assert "processing" in body["error"]["message"]


def test_slow_body_trickle_408(world):
    """A client trickling its body cannot pin the handler past the total
    deadline: the incremental read aborts with 408."""
    import socket as _socket

    gw, _, _ = world
    gw.process_timeout_s = 0.3
    s = _socket.create_connection(("127.0.0.1", gw.port), timeout=10)
    try:
        s.sendall(b"POST /v1/chat/completions HTTP/1.1\r\n"
                  b"Host: x\r\nAuthorization: Bearer sk-alice\r\n"
                  b"Content-Type: application/json\r\n"
                  b"Content-Length: 1000\r\n\r\n")
        t0 = time.monotonic()
        # Trickle a few bytes, then just wait for the server's verdict.
        for _ in range(3):
            s.sendall(b"{")
            time.sleep(0.1)
        s.settimeout(10)
        resp = s.recv(4096)
        assert b"408" in resp.split(b"\r\n")[0]
        assert time.monotonic() - t0 < 5
    finally:
        s.close()


# ---------------------------------------------------------------------------
# SLO tiers (arks_tpu.slo): x-arks-tier validation, forwarding, 503 headers
# ---------------------------------------------------------------------------


def _post_tier(gw, body, tier, token="sk-alice"):
    req = urllib.request.Request(
        f"http://127.0.0.1:{gw.port}/v1/chat/completions",
        data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json",
                 "Authorization": f"Bearer {token}",
                 "x-arks-tier": tier})
    return urllib.request.urlopen(req, timeout=30)


def test_tier_header_rejected_without_ladder(world):
    """With no ARKS_SLO_TIERS configured, a tier header is a config
    mismatch — reject it instead of silently ignoring the QoS ask."""
    gw, _, _ = world
    assert not gw.slo
    code, body = _err(lambda: _post_tier(gw, {"model": "m1"}, "latency"))
    assert code == 400 and "ARKS_SLO_TIERS" in body["error"]["message"]


def test_tier_header_unknown_tier_400(world):
    from arks_tpu import slo as slo_mod
    gw, _, _ = world
    gw.slo = slo_mod.parse_tiers("latency:ttft_ms=300,batch:")
    code, body = _err(lambda: _post_tier(gw, {"model": "m1"}, "bogus"))
    assert code == 400
    assert "bogus" in body["error"]["message"]
    assert "latency" in body["error"]["message"]  # lists the valid ladder


def test_tier_header_forwarded_to_backend(world):
    from arks_tpu import slo as slo_mod
    gw, _, backend = world
    gw.slo = slo_mod.parse_tiers("latency:ttft_ms=300,batch:")
    with _post_tier(gw, {"model": "m1", "messages": []}, "latency") as r:
        assert r.status == 200
    assert backend.requests[-1]["headers"]["x-arks-tier"] == "latency"


def test_rpm_429_carries_retry_after_to_window_edge(world):
    """Every rate-limit 429 carries Retry-After derived from the
    wall-clock window edge (satellite contract: precise backoff, not
    guess-retry) plus the tenant identity header."""
    gw, _, _ = world
    for _ in range(4):
        _post(gw, {"model": "m1"}).read()
    try:
        _post(gw, {"model": "m1"})
        raise AssertionError("expected HTTPError")
    except urllib.error.HTTPError as e:
        assert e.code == 429
        ra = e.headers.get("Retry-After")
        assert ra is not None and 1 <= int(ra) <= 60
        assert e.headers.get("x-arks-tenant") == "team-a/alice"


def test_quota_429_carries_retry_after(world):
    """Quota-exhaustion 429s carry Retry-After too (the syncer's status
    cadence horizon) — BOTH 429 classes are retryable-with-a-clock."""
    gw, store, _ = world
    t = store.get(res.Token, "alice", "team-a")
    t.spec["qos"][0]["rateLimits"] = [{"type": "rpm", "value": 100}]
    store.update(t)
    time.sleep(0.3)
    for _ in range(5):
        _post(gw, {"model": "m1"}).read()
    try:
        _post(gw, {"model": "m1"})
        raise AssertionError("expected HTTPError")
    except urllib.error.HTTPError as e:
        assert e.code == 429
        assert "quota" in json.load(e)["error"]["message"]
        assert e.headers.get("Retry-After") is not None
        assert int(e.headers["Retry-After"]) >= 1
        assert e.headers.get("x-arks-tenant") == "team-a/alice"


def test_tier_capacity_503_carries_retry_after_and_tier(world):
    """A tier-carrying request that hits capacity (no ready backends)
    gets 503 + Retry-After + x-arks-tier, so per-tier clients back off
    independently (satellite contract)."""
    from arks_tpu import slo as slo_mod
    gw, store, _ = world
    gw.slo = slo_mod.parse_tiers("latency:ttft_ms=300,batch:")
    gw.cold_start_wait_s = 0.3
    ep = store.get(res.Endpoint, "m1", "team-a")
    ep.status = {"routes": []}
    store.update(ep)
    time.sleep(0.3)
    try:
        _post_tier(gw, {"model": "m1"}, "latency")
        raise AssertionError("expected HTTPError")
    except urllib.error.HTTPError as e:
        assert e.code == 503
        assert e.headers.get("Retry-After") is not None
        assert e.headers.get("x-arks-tier") == "latency"


# ---------------------------------------------------------------------------
# Tenant-fair admission: identity mint, edge shed, bounded tracker state
# ---------------------------------------------------------------------------


def test_tenant_header_minted_toward_backend(world):
    """The gateway mints x-arks-tenant from the token's resolved
    namespace/username — clients cannot spoof tenant identity by
    sending the header themselves."""
    gw, _, backend = world
    req = urllib.request.Request(
        f"http://127.0.0.1:{gw.port}/v1/chat/completions",
        data=json.dumps({"model": "m1", "messages": []}).encode(),
        headers={"Content-Type": "application/json",
                 "Authorization": "Bearer sk-alice",
                 "x-arks-tenant": "spoofed/identity"})
    urllib.request.urlopen(req, timeout=30).read()
    assert backend.requests[-1]["headers"]["x-arks-tenant"] == "team-a/alice"


def test_edge_shed_rejects_most_over_share_tenant(world):
    """At the in-flight cap the MOST over-share tenant is shed with
    429 + Retry-After + tenant header; an under-share tenant still
    flows (pre-emptive edge protection, not a blanket 429)."""
    gw, _, _ = world
    gw.shed_inflight_max = 5
    # A phantom tenant holds most of the in-flight budget.
    with gw._inflight_lock:
        gw._inflight["team-b/flood"] = 5
    try:
        # alice: prospective share (0+1)/1 = 1 < flood's 5 -> admitted.
        with _post(gw, {"model": "m1", "messages": []}) as r:
            assert r.status == 200
        # Now alice IS the most over-share prospective tenant.
        with gw._inflight_lock:
            gw._inflight.clear()
            gw._inflight["team-a/alice"] = 5
        try:
            _post(gw, {"model": "m1"})
            raise AssertionError("expected HTTPError")
        except urllib.error.HTTPError as e:
            assert e.code == 429
            assert e.headers.get("Retry-After") == "1"
            assert e.headers.get("x-arks-tenant") == "team-a/alice"
            assert "fair share" in json.load(e)["error"]["message"]
        assert gw.metrics.shed_total.get(
            tenant="team-a/alice", reason="inflight_overshare") == 1
    finally:
        gw.shed_inflight_max = 0
        with gw._inflight_lock:
            gw._inflight.clear()


def test_rate_tracker_lru_bound():
    from arks_tpu.gateway.server import RequestRateTracker
    tr = RequestRateTracker(max_keys=3)
    for i in range(3):
        tr.record("ns", f"ep{i}")
    # Touch ep0 so it becomes most-recently-used, then overflow.
    tr.record("ns", "ep0")
    tr.record("ns", "ep3")
    assert len(tr._counts) == 3
    assert tr.rpm("ns", "ep1") == 0.0     # LRU victim: evicted
    assert tr.rpm("ns", "ep0") >= 2.0     # survived via the touch
    assert tr.rpm("ns", "ep3") >= 1.0


def test_ejector_lru_bound():
    from arks_tpu.gateway.server import _Ejector
    ej = _Ejector(max_addrs=4)
    for i in range(1000):
        ej.fail(f"10.0.0.{i}:80")
    assert len(ej._bad) <= 4
    assert len(ej._ejected_until) <= 4


# ---------------------------------------------------------------------------
# SSE metering: exact accounting across mid-stream client disconnect
# ---------------------------------------------------------------------------


class _SlowStreamBackend:
    """Streams SSE frames with a pause before the usage frame so a test
    client can hang up mid-stream.  ``usage_delay_s`` paces the frames;
    with ``send_usage=False`` the stream trickles fillers and never
    delivers usage (the unmetered-giveup case)."""

    def __init__(self, usage_delay_s=0.3, send_usage=True):
        stub = self

        class H(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):
                pass

            def do_POST(self):
                length = int(self.headers.get("Content-Length", 0))
                self.rfile.read(length)
                usage = {"prompt_tokens": PROMPT_TOKENS,
                         "completion_tokens": COMPLETION_TOKENS,
                         "total_tokens": PROMPT_TOKENS + COMPLETION_TOKENS}
                first = (b"data: " + json.dumps(
                    {"id": "x", "choices": [{"delta": {"content": "hi"}}]}
                ).encode() + b"\n\n")
                if stub.send_usage:
                    rest = (b"data: " + json.dumps(
                        {"id": "x", "choices": [], "usage": usage}
                    ).encode() + b"\n\n" + b"data: [DONE]\n\n")
                else:
                    filler = (b"data: " + json.dumps(
                        {"id": "x", "choices": [{"delta": {"content": "z"}}]}
                    ).encode() + b"\n\n")
                    rest = filler * 6 + b"data: [DONE]\n\n"
                self.send_response(200)
                self.send_header("Content-Type", "text/event-stream")
                self.send_header("Content-Length",
                                 str(len(first) + len(rest)))
                self.end_headers()
                self.wfile.write(first)
                self.wfile.flush()
                if stub.send_usage:
                    time.sleep(stub.usage_delay_s)
                    self.wfile.write(rest)
                else:
                    step = len(rest) // 6
                    for i in range(0, len(rest), step):
                        time.sleep(stub.usage_delay_s)
                        try:
                            self.wfile.write(rest[i:i + step])
                            self.wfile.flush()
                        except OSError:
                            return

        self.usage_delay_s, self.send_usage = usage_delay_s, send_usage
        self.httpd = ThreadingHTTPServer(("127.0.0.1", 0), H)
        self.port = self.httpd.server_port
        threading.Thread(target=self.httpd.serve_forever, daemon=True).start()

    @property
    def addr(self):
        return f"127.0.0.1:{self.port}"

    def stop(self):
        self.httpd.shutdown()


def _disconnect_mid_stream(gw, slow):
    """Open a streaming request, read up to the first frame, then RST
    the connection (SO_LINGER 0) so the gateway's next relay write
    fails immediately."""
    import socket as _socket
    import struct as _struct

    body = json.dumps({"model": "m1", "stream": True,
                       "stream_options": {"include_usage": True}}).encode()
    s = _socket.create_connection(("127.0.0.1", gw.port), timeout=10)
    try:
        s.sendall(b"POST /v1/chat/completions HTTP/1.1\r\n"
                  b"Host: x\r\nAuthorization: Bearer sk-alice\r\n"
                  b"Content-Type: application/json\r\n"
                  + f"Content-Length: {len(body)}\r\n\r\n".encode() + body)
        got = b""
        while b"delta" not in got:
            got += s.recv(4096)
    finally:
        s.setsockopt(_socket.SOL_SOCKET, _socket.SO_LINGER,
                     _struct.pack("ii", 1, 0))
        s.close()


def test_disconnect_mid_stream_still_meters_exactly_once(world):
    """Client hangs up after the first SSE frame; the backend only
    emits usage later.  The gateway drains to the usage frame and
    accounts it EXACTLY once — no unmetered leak, no double-count."""
    gw, store, _ = world
    slow = _SlowStreamBackend(usage_delay_s=0.3)
    try:
        ep = store.get(res.Endpoint, "m1", "team-a")
        ep.status["routes"] = [
            {"backend": {"addresses": [slow.addr]}, "weight": 1}]
        store.update_status(ep)
        _disconnect_mid_stream(gw, slow)
        deadline = time.monotonic() + 5
        while (gw.metrics.client_disconnects_total.total() < 1
               and time.monotonic() < deadline):
            time.sleep(0.05)
        assert gw.metrics.client_disconnects_total.total() == 1
        assert gw.metrics.usage_unmetered_total.total() == 0
        # Exactly once: the full usage object, not zero, not doubled.
        deadline = time.monotonic() + 5
        while (gw.quota.get_usage("team-a", "alice-quota").get("total", 0) < 12
               and time.monotonic() < deadline):
            time.sleep(0.05)
        assert gw.quota.get_usage("team-a", "alice-quota")["total"] == 12
    finally:
        slow.stop()


def test_disconnect_drain_window_bounds_the_babysit(world):
    """Client gone AND the backend never sends usage: the gateway gives
    up at ARKS_GW_DISCONNECT_DRAIN_S and records the unmetered leak
    instead of hanging on a dead stream — and nothing is billed."""
    gw, store, _ = world
    slow = _SlowStreamBackend(usage_delay_s=0.25, send_usage=False)
    gw.disconnect_drain_s = 0.3
    try:
        ep = store.get(res.Endpoint, "m1", "team-a")
        ep.status["routes"] = [
            {"backend": {"addresses": [slow.addr]}, "weight": 1}]
        store.update_status(ep)
        t0 = time.monotonic()
        _disconnect_mid_stream(gw, slow)
        deadline = time.monotonic() + 5
        while (gw.metrics.usage_unmetered_total.total() < 1
               and time.monotonic() < deadline):
            time.sleep(0.05)
        assert gw.metrics.usage_unmetered_total.total() == 1
        assert time.monotonic() - t0 < 4, "drain window did not bound"
        assert gw.quota.get_usage("team-a", "alice-quota").get("total", 0) == 0
    finally:
        gw.disconnect_drain_s = 10.0
        slow.stop()
