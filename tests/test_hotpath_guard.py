"""Static guard over the decode hot path.

The zero-host-sync contract of the pipelined scheduler lives or dies on
the ISSUE side of the issue/resolve split never blocking on device
values: one stray ``np.asarray(device_array)`` in an ``_issue_*``
function silently reintroduces the per-step host stall the pipeline
exists to remove — and it would still pass every token-parity test,
because blocking changes only the overlap, not the values.  This test
walks the scheduler's issue-side functions via AST and fails on any new
blocking fetch (np.asarray / jax.device_get / .block_until_ready /
.item) outside the ``_resolve_*`` / ``_pipe_resolve_*`` tails, where
host syncs belong.
"""

import ast
import inspect

from arks_tpu.engine import engine as engine_mod

# The issue-side hot path: one dispatch goes OUT per call, nothing comes
# back.  _resolve_* and _pipe_resolve_* are deliberately absent — they
# are the sanctioned host-sync tails.
HOT_PATH_FUNCTIONS = (
    "step",
    "_step_pipelined",
    "_pipe_issue",
    "_issue_decode",
    "_issue_mixed",
    # Speculative decoding rides the mixed dispatch: the spec-mixed issue
    # path (and the chunk-lane builder both mixed issuers share) must not
    # grow a blocking fetch either — draft proposals are scattered into
    # the verify blocks ON DEVICE precisely so no host sync is needed.
    "_issue_spec_mixed",
    "_fill_chunk_lanes",
    "_issue_admit_batch",
    # Hierarchical prefix cache: spills and restores are ISSUE-side too —
    # eviction must never block the engine thread, and a restore is just
    # another async dispatch the pipelined decode overlaps.  Their host
    # syncs live in _resolve_spills / _resolve_restores.
    "_spill_flush",
    "_issue_restore",
    "_dispatch_restore_group",
    # Multi-model serving: the switch issue path runs every step while
    # another model's weights stream in the background — a blocking fetch
    # here would stall the pipelined decode the overlap exists to protect.
    # The load itself happens on a pool thread; the switch executes only
    # at a fully drained boundary (nothing in flight to stall).
    "_issue_model_load",
    "_park_awaiting_model",
    # Routing-sketch membership maintenance rides these engine-thread
    # paths (the allocator's mirror updates inside register/evict): they
    # must stay pure host bookkeeping — the sketch EXPORT happens on
    # server threads from the mirror, never by fetching device state here.
    "_note_evicted",
    "_register_prompt_pages",
    # Preemptive KV swap: the seize path runs INSIDE a loaded step — the
    # victim's KV gathers and sampler-row snapshot go out as async
    # dispatches (copy_to_host_async) and the resume scatter is the same
    # async restore program as prefix restores.  A blocking fetch here
    # would stall every survivor's decode for the length of a D2H drain.
    # Host syncs live in _resolve_preempt_swaps / _finish_resume (via
    # _resolve_restores).
    "_maybe_preempt",
    "_issue_preempt_swap",
    "_preempt_replay",
    "_service_swapped",
    "_resume_swapped",
    # Ragged-grid padding-waste counters: both mixed issuers call this per
    # dispatch.  It reads the host-side numpy batch arrays the issuer
    # already built — fetching device state here would reintroduce the
    # per-step stall on every single mixed dispatch.
    "_mixed_grid_counters",
)

# Sketch export surface: runs on SERVER threads, but the same contract
# applies with more force — an export that fetched device data would
# serialize against the dispatch stream from outside the engine thread.
# Everything it reads (digest mirrors, host-tier maps, counters) is host
# state by construction.
SKETCH_EXPORT_FUNCTIONS = (
    "cache_sketch",
    "note_prompt_text",
)

# Sanctioned exceptions, keyed (function, unparsed argument).  Each entry
# must stay justifiable as a NON-blocking read:
#   - _fill_chunk_lanes / st.key: an 8-byte PRNG key materialized at
#     _start_chunked, long before any in-flight dispatch could pin it.
#   - _issue_admit_batch / slots_l: a host python list, not device data.
ALLOWED = {
    ("_fill_chunk_lanes", "st.key"),
    ("_issue_admit_batch", "slots_l"),
}

BLOCKING_ATTRS = {"block_until_ready", "item"}


def _blocking_calls(func_name: str, tree: ast.AST):
    out = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        if not isinstance(f, ast.Attribute):
            continue
        hit = None
        if (f.attr == "asarray" and isinstance(f.value, ast.Name)
                and f.value.id == "np"):
            hit = "np.asarray"
        elif f.attr == "device_get":
            hit = "device_get"
        elif f.attr in BLOCKING_ATTRS:
            hit = f.attr
        if hit is None:
            continue
        arg = ast.unparse(node.args[0]) if node.args else ""
        # Literal host containers are host data by construction.
        if node.args and isinstance(node.args[0],
                                    (ast.List, ast.ListComp, ast.Tuple,
                                     ast.GeneratorExp, ast.Constant)):
            continue
        if (func_name, arg) in ALLOWED:
            continue
        out.append((func_name, hit, arg, node.lineno))
    return out


def test_no_blocking_fetches_on_the_issue_path():
    src = inspect.getsource(engine_mod)
    module = ast.parse(src)
    cls = next(n for n in module.body
               if isinstance(n, ast.ClassDef) and n.name == "InferenceEngine")
    funcs = {n.name: n for n in cls.body if isinstance(n, ast.FunctionDef)}
    missing = [f for f in HOT_PATH_FUNCTIONS if f not in funcs]
    assert not missing, f"hot-path functions renamed/removed: {missing}"

    violations = []
    for name in HOT_PATH_FUNCTIONS:
        violations += _blocking_calls(name, funcs[name])
    assert not violations, (
        "blocking device fetch on the issue-side hot path (move it into a "
        f"_resolve_* tail or justify it in ALLOWED): {violations}")


def test_no_blocking_fetches_in_sketch_export():
    """The sketch export path (GET /v1/cache/sketch -> engine.cache_sketch,
    plus the server's note_prompt_text hook) must never grow a blocking
    device fetch: it runs concurrently with the dispatch stream, with the
    same non-blocking discipline as spills."""
    src = inspect.getsource(engine_mod)
    module = ast.parse(src)
    cls = next(n for n in module.body
               if isinstance(n, ast.ClassDef) and n.name == "InferenceEngine")
    funcs = {n.name: n for n in cls.body if isinstance(n, ast.FunctionDef)}
    missing = [f for f in SKETCH_EXPORT_FUNCTIONS if f not in funcs]
    assert not missing, f"sketch export functions renamed/removed: {missing}"
    violations = []
    for name in SKETCH_EXPORT_FUNCTIONS:
        violations += _blocking_calls(name, funcs[name])
    assert not violations, (
        f"blocking device fetch in the sketch export path: {violations}")


def test_sketch_module_stays_jax_free():
    """The router imports arks_tpu.prefix_sketch directly — a jax (or
    arks_tpu.engine) import there would drag the full runtime into the
    pure-I/O router process."""
    import arks_tpu.prefix_sketch as sketch_mod
    src = inspect.getsource(sketch_mod)
    module = ast.parse(src)
    for node in ast.walk(module):
        names = []
        if isinstance(node, ast.Import):
            names = [a.name for a in node.names]
        elif isinstance(node, ast.ImportFrom):
            names = [node.module or ""]
        for n in names:
            assert not n.startswith("jax"), f"jax import in prefix_sketch: {n}"
            assert not n.startswith("arks_tpu.engine"), (
                f"engine import in prefix_sketch: {n}")


def test_no_blocking_fetches_in_stream_scatter_helpers():
    """The weight-streaming scatter path (models.weights) issues its H2D
    puts as ordinary async dispatches while the live engine keeps
    decoding; a blocking fetch there would serialize the overlap the
    streaming switch exists for."""
    from arks_tpu.models import weights as weights_mod
    src = inspect.getsource(weights_mod)
    module = ast.parse(src)
    funcs = {n.name: n for n in module.body
             if isinstance(n, ast.FunctionDef)}
    guarded = ("_shard_put_fns", "stream_params_to_device")
    missing = [f for f in guarded if f not in funcs]
    assert not missing, f"stream-scatter helpers renamed/removed: {missing}"
    violations = []
    for name in guarded:
        violations += _blocking_calls(name, funcs[name])
    assert not violations, (
        f"blocking device fetch in the weight-streaming path: {violations}")


def _module_funcs(mod, names):
    """FunctionDef nodes for module-level functions, asserting presence."""
    src = inspect.getsource(mod)
    tree = ast.parse(src)
    funcs = {n.name: n for n in tree.body if isinstance(n, ast.FunctionDef)}
    missing = [f for f in names if f not in funcs]
    assert not missing, f"guarded helpers renamed/removed: {missing}"
    return [funcs[n] for n in names]


# Work-list / grid-plan helpers that run per mixed dispatch (the ragged
# grid's launch-parameter resolution), plus the autotune CACHE-LOAD path
# that mixed_grid_plan consults.  All of them sit upstream of every mixed
# issue — same zero-host-sync contract as the issuers themselves.
# build_mixed_work_list is traceable jnp on purpose (the pipelined
# dispatches derive q_len on device); mixed_grid_steps deliberately takes
# already-host numpy without np.asarray.
GRID_PLAN_HELPERS = {
    "arks_tpu.ops.paged_attention": (
        "mixed_grid_mode", "mixed_grid_plan", "build_mixed_work_list"),
    "arks_tpu.engine.paged": ("mixed_grid_steps",),
    "arks_tpu.ops.autotune": ("lookup", "_load_locked", "mixed_signature",
                              "decode_signature"),
}


def test_no_blocking_fetches_in_grid_plan_helpers():
    import importlib
    violations = []
    for mod_name, names in GRID_PLAN_HELPERS.items():
        mod = importlib.import_module(mod_name)
        for node in _module_funcs(mod, names):
            violations += _blocking_calls(f"{mod_name}.{node.name}", node)
    assert not violations, (
        f"blocking device fetch in a grid-plan/autotune-load helper: "
        f"{violations}")


def test_no_sweep_reachable_from_step_loop():
    """The autotune lookup/ensure split: the step loop (hot-path issuers
    and the grid-plan helpers they call) may only ever take the PURE READ
    side (autotune.lookup).  A sweep() or ensure() call — which compiles
    and times candidate kernels — belongs exclusively in warm-up
    (_warm_autotune, before the first dispatch)."""
    import importlib

    def sweep_calls(func_name, tree):
        out = []
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            hit = None
            if isinstance(f, ast.Attribute):
                # autotune.sweep / autotune.ensure / self._warm_autotune;
                # other receivers' ensure (e.g. the weight pool's
                # pool.ensure) are unrelated.
                recv = ast.unparse(f.value)
                if f.attr == "_warm_autotune" or (
                        f.attr in ("sweep", "ensure")
                        and recv.split(".")[-1] == "autotune"):
                    hit = f"{recv}.{f.attr}"
            elif isinstance(f, ast.Name) and f.id in ("sweep", "ensure",
                                                      "_warm_autotune"):
                hit = f.id
            if hit:
                out.append((func_name, hit, node.lineno))
        return out

    src = inspect.getsource(engine_mod)
    module = ast.parse(src)
    cls = next(n for n in module.body
               if isinstance(n, ast.ClassDef) and n.name == "InferenceEngine")
    funcs = {n.name: n for n in cls.body if isinstance(n, ast.FunctionDef)}
    violations = []
    for name in HOT_PATH_FUNCTIONS:
        violations += sweep_calls(name, funcs[name])
    for mod_name, names in GRID_PLAN_HELPERS.items():
        mod = importlib.import_module(mod_name)
        for node in _module_funcs(mod, names):
            violations += sweep_calls(f"{mod_name}.{node.name}", node)
    assert not violations, (
        f"autotune sweep reachable from the step loop: {violations}")


def test_trace_calls_on_hot_path_are_evt_only():
    """The step loop may talk to the tracer through exactly one method:
    ``self.trace.evt(...)`` — an append to a per-thread ring.  Any other
    tracer attribute reached from a hot-path function (flush, register,
    attach_tail, store access...) takes locks or allocates, i.e. it is
    trace ASSEMBLY leaking onto the issue path."""
    src = inspect.getsource(engine_mod)
    module = ast.parse(src)
    cls = next(n for n in module.body
               if isinstance(n, ast.ClassDef) and n.name == "InferenceEngine")
    funcs = {n.name: n for n in cls.body if isinstance(n, ast.FunctionDef)}
    violations = []
    for name in HOT_PATH_FUNCTIONS:
        for node in ast.walk(funcs[name]):
            if not isinstance(node, ast.Attribute):
                continue
            v = node.value
            if (isinstance(v, ast.Attribute) and v.attr == "trace"
                    and isinstance(v.value, ast.Name)
                    and v.value.id == "self"
                    and node.attr not in ("evt", "enabled")):
                violations.append((name, f"self.trace.{node.attr}",
                                   node.lineno))
    assert not violations, (
        f"non-evt tracer access on the issue-side hot path: {violations}")


def test_tracer_evt_is_lock_and_serialization_free():
    """``Tracer.evt`` and the ``_Ring`` it appends to are the only tracing
    code the step loop executes.  They must stay free of locks, context
    managers, serialization, and sleeps — the single sanctioned exception
    is the first-call-per-thread ring creation inside the AttributeError
    handler (``self._new_ring()``, which takes the registration lock once
    per thread lifetime, not per event)."""
    from arks_tpu.obs import trace as trace_mod

    src = inspect.getsource(trace_mod)
    module = ast.parse(src)
    classes = {n.name: n for n in module.body if isinstance(n, ast.ClassDef)}
    tracer = classes["Tracer"]
    ring = classes["_Ring"]
    evt = next(n for n in tracer.body
               if isinstance(n, ast.FunctionDef) and n.name == "evt")

    def handler_nodes(tree):
        inside = set()
        for node in ast.walk(tree):
            if isinstance(node, ast.ExceptHandler):
                for sub in ast.walk(node):
                    inside.add(id(sub))
        return inside

    violations = []
    for scope_name, tree in (("Tracer.evt", evt), ("_Ring", ring)):
        allowed = handler_nodes(tree)
        for node in ast.walk(tree):
            if id(node) in allowed:
                continue
            bad = None
            if isinstance(node, (ast.With, ast.AsyncWith)):
                bad = "with-block (lock?)"
            elif isinstance(node, ast.Attribute) and node.attr in (
                    "acquire", "Lock", "RLock", "sleep", "dumps", "loads",
                    "flush", "join"):
                bad = f".{node.attr}"
            elif isinstance(node, ast.Name) and node.id in ("json", "pickle"):
                bad = node.id
            if bad:
                violations.append((scope_name, bad, node.lineno))
    assert not violations, (
        f"lock/serialization on the event-record path: {violations}")


def test_resolve_tails_exist():
    """The guard above is only meaningful while the sanctioned sync tails
    exist under their expected names."""
    for name in ("_resolve_decode", "_resolve_mixed", "_resolve_spec_mixed",
                 "_pipe_resolve_one", "_resolve_admit_batch",
                 "_resolve_spills", "_resolve_restores",
                 "_resolve_preempt_swaps", "_finish_resume"):
        assert callable(getattr(engine_mod.InferenceEngine, name)), name
