"""Static guard over the decode hot path — thin wrapper over arkslint.

The invariants this file used to implement by hand (zero-host-sync issue
path, autotune-sweep containment, evt-only tracing, jax-free sketch
module) now live in ``arks_tpu/analysis/rules/hotpath.py``, which
discovers the issue-side hot path by CALL GRAPH from the scheduler roots
instead of the hand-curated ``HOT_PATH_FUNCTIONS`` tuple this file used
to carry — a new helper cannot dodge the guard by not being listed.
Reviewed exceptions (the old ``ALLOWED`` set) live in
``tools/arkslint-baseline.json`` with one-line justifications.

These wrappers keep ``pytest tests/`` and ``python -m arks_tpu.analysis``
two doors into the same checker: each test filters the rule's findings
by sub-check so a regression still fails the test whose name says what
broke.  The call-graph discovery itself (including the guarantee that it
covers everything the legacy tuple listed) is tested in
``tests/test_analysis.py``.
"""

import functools

from arks_tpu.analysis import SourceTree, repo_root, run_rules
from arks_tpu.analysis.baseline import Baseline


@functools.lru_cache(maxsize=1)
def _active_findings():
    """hotpath findings over the real tree, baseline applied (staleness
    is asserted by test_analysis.py / the CLI, not per-wrapper)."""
    root = repo_root()
    findings = run_rules(SourceTree.load(root), ["hotpath"])
    baseline = Baseline.load(root / "tools" / "arkslint-baseline.json")
    active, _suppressed, _stale = baseline.apply(findings)
    return [f for f in active if f.severity == "error"]


def _errors(*checks):
    return [f.render() for f in _active_findings() if f.check in checks]


def test_no_blocking_fetches_on_the_issue_path():
    assert not _errors("blocking-fetch"), _errors("blocking-fetch")


def test_no_sweep_reachable_from_step_loop():
    assert not _errors("autotune-sweep"), _errors("autotune-sweep")


def test_no_serialization_on_the_issue_path():
    assert not _errors("serialization", "lock-acquire"), (
        _errors("serialization", "lock-acquire"))


def test_trace_calls_on_hot_path_are_evt_only():
    assert not _errors("trace-access"), _errors("trace-access")


def test_tracer_evt_is_lock_and_serialization_free():
    assert not _errors("trace-evt-impl"), _errors("trace-evt-impl")


def test_sketch_module_stays_jax_free():
    assert not _errors("sketch-import"), _errors("sketch-import")


def test_resolve_tails_exist():
    """Roots and sanctioned host-sync tails still exist under their
    expected names — the guard is only meaningful while they do."""
    assert not _errors("contract"), _errors("contract")
