"""Speculative decoding: draft proposes, target verifies in one pass.

The load-bearing invariant: GREEDY speculative output is IDENTICAL to
target-only greedy output — the draft only changes how many tokens land
per dispatch, never which tokens.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from arks_tpu.engine import EngineConfig, InferenceEngine, Request, SamplingParams
from arks_tpu.engine.tokenizer import ByteTokenizer
from arks_tpu.models import get_config, transformer as tf


def _drive(engine, n_steps=300):
    for _ in range(n_steps):
        engine.step(block_s=0.01)
        if (engine.num_running == 0 and engine._queue.empty()
                and not engine._prefilling):
            break


def _collect(req, timeout=60):
    ids, fin = [], None
    while True:
        out = req.outputs.get(timeout=timeout)
        ids.extend(out.token_ids)
        if out.finished:
            return ids, out


def _run(draft_model, prompts, max_tokens=12, temperature=0.0, seed=None,
         draft_len=4):
    cfg = get_config("tiny")
    ecfg = EngineConfig(model="tiny", num_slots=4, max_cache_len=64,
                        prefill_buckets=(16, 32), steps_per_dispatch=4,
                        draft_model=draft_model, draft_len=draft_len,
                        prefix_cache_mb=0)
    eng = InferenceEngine(cfg, ecfg, ByteTokenizer())
    reqs = [Request(f"r{i}", p, SamplingParams(
        max_tokens=max_tokens, temperature=temperature, seed=seed,
        ignore_eos=True)) for i, p in enumerate(prompts)]
    for r in reqs:
        eng.add_request(r)
    _drive(eng)
    return [_collect(r)[0] for r in reqs], eng


PROMPTS = [[5, 6, 7, 8, 9], [20, 21, 22], [3] * 18]


def test_greedy_exactness_vs_baseline():
    """Draft ("tiny-gqa", a DIFFERENT model) -> imperfect acceptance, but
    byte-identical greedy output."""
    base, _ = _run(None, PROMPTS)
    spec, eng = _run("tiny-gqa", PROMPTS)
    assert spec == base
    # The spec path actually ran and accounted its proposals.
    assert eng._spec_proposed > 0
    text = eng.metrics.registry.render()
    assert "spec_decode_acceptance_rate" in text


def test_self_draft_accepts_everything():
    """Draft sharing the target's WEIGHTS: every proposal matches, so each
    dispatch lands the full draft block and acceptance is ~100%."""
    import jax

    base, _ = _run(None, PROMPTS[:1], max_tokens=12)
    cfg = get_config("tiny")
    params = tf.init_params(cfg, jax.random.PRNGKey(0))
    ecfg = EngineConfig(model="tiny", num_slots=4, max_cache_len=64,
                        prefill_buckets=(16, 32), steps_per_dispatch=4,
                        draft_model="tiny", draft_len=4, prefix_cache_mb=0)
    eng = InferenceEngine(cfg, ecfg, ByteTokenizer(), params=params,
                          draft_params=params, draft_cfg=cfg)
    req = Request("r0", PROMPTS[0], SamplingParams(max_tokens=12,
                                                   temperature=0.0,
                                                   ignore_eos=True))
    eng.add_request(req)
    _drive(eng)
    ids, _ = _collect(req)
    assert ids == base[0]
    assert eng._spec_accepted == eng._spec_proposed > 0


def test_sampled_requests_fall_back():
    """temperature > 0 dispatches use the normal fused loop (and still
    produce valid tokens)."""
    cfg = get_config("tiny")
    spec, eng = _run("tiny-gqa", PROMPTS[:1], temperature=0.8, seed=3)
    assert eng._spec_proposed == 0  # never took the spec path
    assert len(spec[0]) == 12
    assert all(0 <= t < cfg.vocab_size for t in spec[0])


def test_stop_token_mid_block():
    """A stop token inside an accepted block truncates the output there."""
    base, _ = _run(None, PROMPTS[:1], max_tokens=40)
    stop_tok = base[0][5]
    cfg = get_config("tiny")
    ecfg = EngineConfig(model="tiny", num_slots=2, max_cache_len=64,
                        prefill_buckets=(16, 32), draft_model="tiny",
                        draft_len=4, prefix_cache_mb=0)
    eng = InferenceEngine(cfg, ecfg, ByteTokenizer())
    req = Request("s", PROMPTS[0], SamplingParams(
        max_tokens=40, temperature=0.0, ignore_eos=True,
        stop_token_ids=[stop_tok]))
    eng.add_request(req)
    _drive(eng)
    ids, fin = _collect(req)
    assert fin.finish_reason == "stop"
    assert ids == base[0][:5]  # truncated before the stop token


def test_verify_step_matches_sequential_decode():
    cfg = get_config("tiny")
    params = tf.init_params(cfg, __import__("jax").random.PRNGKey(0), jnp.float32)
    import jax
    B, K, L0 = 2, 4, 9
    cache_a = tf.init_cache(cfg, B, 32, jnp.float32)
    cache_b = tf.init_cache(cfg, B, 32, jnp.float32)
    toks0 = jax.random.randint(jax.random.PRNGKey(1), (1, L0), 0, cfg.vocab_size)
    _, ks, vs = tf.prefill(params, cfg, toks0, jnp.asarray([L0], jnp.int32))
    for s in range(B):
        cache_a = tf.insert(cache_a, ks, vs, jnp.asarray(s))
        cache_b = tf.insert(cache_b, ks, vs, jnp.asarray(s))
    block = jax.random.randint(jax.random.PRNGKey(2), (B, K), 0, cfg.vocab_size)
    lengths = jnp.full((B,), L0, jnp.int32)
    seq = []
    ca, ln = cache_a, lengths
    for i in range(K):
        lg, ca = tf.decode_step(params, cfg, ca, block[:, i], ln)
        seq.append(lg)
        ln = ln + 1
    seq = jnp.stack(seq, axis=1)
    ver, cb = tf.verify_step(params, cfg, cache_b, block, lengths)
    np.testing.assert_allclose(np.asarray(seq), np.asarray(ver), atol=1e-5)
    np.testing.assert_allclose(np.asarray(ca.k), np.asarray(cb.k), atol=1e-6)


def test_spec_decode_config_validation():
    cfg = get_config("tiny")
    with pytest.raises(ValueError, match="draft_len"):
        InferenceEngine(cfg, EngineConfig(model="tiny", draft_model="tiny",
                                          draft_len=1), ByteTokenizer())
    with pytest.raises(ValueError, match="pipeline_parallel"):
        InferenceEngine(cfg, EngineConfig(model="tiny", draft_model="tiny",
                                          pipeline_parallel=2),
                        ByteTokenizer())


def test_mixed_batch_marks_drafts_stale():
    """Greedy slots that advanced via the fused loop (forced by a sampled
    co-resident request) must NOT take the spec path afterwards — their
    draft mirrors are stale and would mispredict every token."""
    cfg = get_config("tiny")
    ecfg = EngineConfig(model="tiny", num_slots=2, max_cache_len=64,
                        prefill_buckets=(16, 32), steps_per_dispatch=2,
                        draft_model="tiny-gqa", draft_len=4,
                        prefix_cache_mb=0)
    eng = InferenceEngine(cfg, ecfg, ByteTokenizer())
    greedy = Request("g", PROMPTS[0], SamplingParams(max_tokens=30,
                                                     temperature=0.0,
                                                     ignore_eos=True))
    sampled = Request("s", PROMPTS[1], SamplingParams(max_tokens=4,
                                                      temperature=0.9,
                                                      seed=1,
                                                      ignore_eos=True))
    eng.add_request(greedy)
    eng.add_request(sampled)
    _drive(eng)
    _collect(greedy)
    _collect(sampled)
    # The greedy slot rode the fused loop throughout the mixed phase and
    # stayed there once marked stale — the spec path never fired.
    assert eng._spec_proposed == 0


def test_long_prompt_skips_draft_prefill():
    """Prompts beyond the one-shot buckets skip the (monolithic) draft
    prefill and ride the fused loop — no head-of-line draft stall."""
    cfg = get_config("tiny")
    ecfg = EngineConfig(model="tiny", num_slots=2, max_cache_len=64,
                        prefill_buckets=(16,), steps_per_dispatch=2,
                        prefill_chunk=16, draft_model="tiny-gqa",
                        draft_len=4, prefix_cache_mb=0)
    eng = InferenceEngine(cfg, ecfg, ByteTokenizer())
    long_prompt = [int(x) % cfg.vocab_size for x in range(3, 45)]  # 42 > 16
    r = Request("l", long_prompt, SamplingParams(max_tokens=4,
                                                 temperature=0.0,
                                                 ignore_eos=True))
    eng.add_request(r)
    _drive(eng)
    ids, fin = _collect(r)
    assert fin.num_prompt_tokens == 42 and len(ids) == 4
    assert eng._spec_proposed == 0  # slot never draft-synced
