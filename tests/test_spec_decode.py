"""Speculative decoding: draft proposes, target verifies in one pass.

The load-bearing invariant: GREEDY speculative output is IDENTICAL to
target-only greedy output — the draft only changes how many tokens land
per dispatch, never which tokens.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from arks_tpu.engine import EngineConfig, InferenceEngine, Request, SamplingParams
from arks_tpu.engine.tokenizer import ByteTokenizer
from arks_tpu.models import get_config, transformer as tf


def _drive(engine, n_steps=300):
    for _ in range(n_steps):
        engine.step(block_s=0.01)
        if (engine.num_running == 0 and engine._queue.empty()
                and not engine._prefilling):
            break


def _collect(req, timeout=60):
    ids, fin = [], None
    while True:
        out = req.outputs.get(timeout=timeout)
        ids.extend(out.token_ids)
        if out.finished:
            return ids, out


def _run(draft_model, prompts, max_tokens=12, temperature=0.0, seed=None,
         draft_len=4):
    cfg = get_config("tiny")
    ecfg = EngineConfig(model="tiny", num_slots=4, max_cache_len=64,
                        prefill_buckets=(16, 32), steps_per_dispatch=4,
                        draft_model=draft_model, draft_len=draft_len,
                        prefix_cache_mb=0)
    eng = InferenceEngine(cfg, ecfg, ByteTokenizer())
    reqs = [Request(f"r{i}", p, SamplingParams(
        max_tokens=max_tokens, temperature=temperature, seed=seed,
        ignore_eos=True)) for i, p in enumerate(prompts)]
    for r in reqs:
        eng.add_request(r)
    _drive(eng)
    return [_collect(r)[0] for r in reqs], eng


PROMPTS = [[5, 6, 7, 8, 9], [20, 21, 22], [3] * 18]


def test_greedy_exactness_vs_baseline():
    """Draft ("tiny-gqa", a DIFFERENT model) -> imperfect acceptance, but
    byte-identical greedy output."""
    base, _ = _run(None, PROMPTS)
    spec, eng = _run("tiny-gqa", PROMPTS)
    assert spec == base
    # The spec path actually ran and accounted its proposals.
    assert eng._spec_proposed > 0
    text = eng.metrics.registry.render()
    assert "spec_decode_acceptance_rate" in text


def test_self_draft_accepts_everything():
    """Draft sharing the target's WEIGHTS: every proposal matches, so each
    dispatch lands the full draft block and acceptance is ~100%."""
    import jax

    base, _ = _run(None, PROMPTS[:1], max_tokens=12)
    cfg = get_config("tiny")
    params = tf.init_params(cfg, jax.random.PRNGKey(0))
    ecfg = EngineConfig(model="tiny", num_slots=4, max_cache_len=64,
                        prefill_buckets=(16, 32), steps_per_dispatch=4,
                        draft_model="tiny", draft_len=4, prefix_cache_mb=0)
    eng = InferenceEngine(cfg, ecfg, ByteTokenizer(), params=params,
                          draft_params=params, draft_cfg=cfg)
    req = Request("r0", PROMPTS[0], SamplingParams(max_tokens=12,
                                                   temperature=0.0,
                                                   ignore_eos=True))
    eng.add_request(req)
    _drive(eng)
    ids, _ = _collect(req)
    assert ids == base[0]
    assert eng._spec_accepted == eng._spec_proposed > 0


def test_sampled_requests_ride_spec_path():
    """temperature > 0 slots take the rejection-sampled spec path: valid
    tokens, deterministic per seed, proposals accounted."""
    cfg = get_config("tiny")
    out1, eng = _run("tiny-gqa", PROMPTS[:1], temperature=0.8, seed=3)
    assert eng._spec_proposed > 0  # the spec path DID fire
    assert len(out1[0]) == 12
    assert all(0 <= t < cfg.vocab_size for t in out1[0])
    # Same seed, same engine shape -> same token stream.
    out2, _ = _run("tiny-gqa", PROMPTS[:1], temperature=0.8, seed=3)
    assert out2 == out1


def test_speculative_accept_distribution_exact():
    """Brute-force the rejection kernel: over many trials the emitted first
    token's empirical distribution matches the target's effective sampling
    distribution (the Leviathan guarantee), for a draft that is WRONG."""
    import jax

    from arks_tpu.engine import sampler as sm

    V, K, N = 12, 3, 4000
    rng = np.random.default_rng(0)
    t_logits = jnp.asarray(rng.standard_normal((1, K, V)), jnp.float32)
    d_logits = jnp.asarray(rng.standard_normal((1, V)), jnp.float32)
    state = sm.init_sampling_state(1, seed=0, vocab_size=V)._replace(
        temperature=jnp.asarray([1.0]))

    @jax.jit
    def one_trial(key):
        keys = key[None]
        tok, q, qp, qi, keys = sm.draft_sample(d_logits, state, keys)
        # Second draft step from the same (stale) draft dist — a crude but
        # legal proposer.
        tok2, q2, qp2, qi2, keys = sm.draft_sample(d_logits, state, keys)
        drafts = jnp.stack([tok, tok2], axis=1)          # [1, K-1]
        q_sel = jnp.stack([q, q2], axis=1)
        q_probs = jnp.stack([qp, qp2], axis=1)
        q_idx = jnp.stack([qi, qi2], axis=1)
        out, counts, _, _ = sm.speculative_accept(
            drafts, q_sel, q_probs, q_idx, t_logits, state, keys)
        return out[0, 0]  # the FIRST emitted token

    keys = jax.random.split(jax.random.PRNGKey(42), N)
    toks = np.asarray(jax.vmap(one_trial)(keys))
    emp = np.bincount(toks, minlength=V) / N
    expected = np.asarray(sm.filtered_probs(t_logits[:, 0], state)[0][0])
    # Map window order back to vocab order.
    idx = np.asarray(sm.filtered_probs(t_logits[:, 0], state)[1][0])
    exp_vocab = np.zeros(V)
    exp_vocab[idx] = expected
    tv = 0.5 * np.abs(emp - exp_vocab).sum()
    assert tv < 0.05, f"total variation {tv:.3f} vs target dist"


def test_stop_token_mid_block():
    """A stop token inside an accepted block truncates the output there."""
    base, _ = _run(None, PROMPTS[:1], max_tokens=40)
    stop_tok = base[0][5]
    cfg = get_config("tiny")
    ecfg = EngineConfig(model="tiny", num_slots=2, max_cache_len=64,
                        prefill_buckets=(16, 32), draft_model="tiny",
                        draft_len=4, prefix_cache_mb=0)
    eng = InferenceEngine(cfg, ecfg, ByteTokenizer())
    req = Request("s", PROMPTS[0], SamplingParams(
        max_tokens=40, temperature=0.0, ignore_eos=True,
        stop_token_ids=[stop_tok]))
    eng.add_request(req)
    _drive(eng)
    ids, fin = _collect(req)
    assert fin.finish_reason == "stop"
    assert ids == base[0][:5]  # truncated before the stop token


def test_verify_step_matches_sequential_decode():
    cfg = get_config("tiny")
    params = tf.init_params(cfg, __import__("jax").random.PRNGKey(0), jnp.float32)
    import jax
    B, K, L0 = 2, 4, 9
    cache_a = tf.init_cache(cfg, B, 32, jnp.float32)
    cache_b = tf.init_cache(cfg, B, 32, jnp.float32)
    toks0 = jax.random.randint(jax.random.PRNGKey(1), (1, L0), 0, cfg.vocab_size)
    _, ks, vs = tf.prefill(params, cfg, toks0, jnp.asarray([L0], jnp.int32))
    for s in range(B):
        cache_a = tf.insert(cache_a, ks, vs, jnp.asarray(s))
        cache_b = tf.insert(cache_b, ks, vs, jnp.asarray(s))
    block = jax.random.randint(jax.random.PRNGKey(2), (B, K), 0, cfg.vocab_size)
    lengths = jnp.full((B,), L0, jnp.int32)
    seq = []
    ca, ln = cache_a, lengths
    for i in range(K):
        lg, ca = tf.decode_step(params, cfg, ca, block[:, i], ln)
        seq.append(lg)
        ln = ln + 1
    seq = jnp.stack(seq, axis=1)
    ver, cb = tf.verify_step(params, cfg, cache_b, block, lengths)
    np.testing.assert_allclose(np.asarray(seq), np.asarray(ver), atol=1e-5)
    np.testing.assert_allclose(np.asarray(ca.k), np.asarray(cb.k), atol=1e-6)


def test_spec_decode_config_validation():
    cfg = get_config("tiny")
    with pytest.raises(ValueError, match="draft_len"):
        InferenceEngine(cfg, EngineConfig(model="tiny", draft_model="tiny",
                                          draft_len=1), ByteTokenizer())
    with pytest.raises(ValueError, match="pipeline_parallel"):
        InferenceEngine(cfg, EngineConfig(model="tiny", draft_model="tiny",
                                          pipeline_parallel=2),
                        ByteTokenizer())


def test_mixed_batch_greedy_exactness():
    """Greedy and sampled slots share spec dispatches (rejection kernel
    handles both); the greedy request's output must STILL be byte-identical
    to the target-only baseline."""
    base, _ = _run(None, [PROMPTS[0]], max_tokens=20)
    cfg = get_config("tiny")
    ecfg = EngineConfig(model="tiny", num_slots=2, max_cache_len=64,
                        prefill_buckets=(16, 32), steps_per_dispatch=2,
                        draft_model="tiny-gqa", draft_len=4,
                        prefix_cache_mb=0)
    eng = InferenceEngine(cfg, ecfg, ByteTokenizer())
    greedy = Request("g", PROMPTS[0], SamplingParams(max_tokens=20,
                                                     temperature=0.0,
                                                     ignore_eos=True))
    sampled = Request("s", PROMPTS[1], SamplingParams(max_tokens=20,
                                                      temperature=0.9,
                                                      seed=1,
                                                      ignore_eos=True))
    eng.add_request(greedy)
    eng.add_request(sampled)
    _drive(eng)
    g_ids, _ = _collect(greedy)
    s_ids, _ = _collect(sampled)
    assert eng._spec_proposed > 0      # mixed batch rode the spec path
    assert g_ids == base[0]            # greedy exactness survives company
    assert len(s_ids) == 20
    assert all(0 <= t < cfg.vocab_size for t in s_ids)


def test_long_prompt_skips_draft_prefill():
    """Prompts beyond the one-shot buckets skip the (monolithic) draft
    prefill and ride the fused loop — no head-of-line draft stall."""
    cfg = get_config("tiny")
    ecfg = EngineConfig(model="tiny", num_slots=2, max_cache_len=64,
                        prefill_buckets=(16,), steps_per_dispatch=2,
                        prefill_chunk=16, draft_model="tiny-gqa",
                        draft_len=4, prefix_cache_mb=0)
    eng = InferenceEngine(cfg, ecfg, ByteTokenizer())
    long_prompt = [int(x) % cfg.vocab_size for x in range(3, 45)]  # 42 > 16
    r = Request("l", long_prompt, SamplingParams(max_tokens=4,
                                                 temperature=0.0,
                                                 ignore_eos=True))
    eng.add_request(r)
    _drive(eng)
    ids, fin = _collect(r)
    assert fin.num_prompt_tokens == 42 and len(ids) == 4
    assert eng._spec_proposed == 0  # slot never draft-synced


def test_penalized_requests_use_fused_path():
    """Presence/frequency penalties evolve per-token counts, which the spec
    kernel doesn't model within a block — penalized slots must ride the
    fused loop (correct penalties beat the draft speedup)."""
    cfg = get_config("tiny")
    ecfg = EngineConfig(model="tiny", num_slots=2, max_cache_len=64,
                        prefill_buckets=(16, 32), draft_model="tiny-gqa",
                        draft_len=4, prefix_cache_mb=0)
    eng = InferenceEngine(cfg, ecfg, ByteTokenizer())
    req = Request("pen", PROMPTS[0], SamplingParams(
        max_tokens=10, temperature=0.0, ignore_eos=True,
        frequency_penalty=1.0))
    eng.add_request(req)
    _drive(eng)
    ids, _ = _collect(req)
    assert len(ids) == 10
    assert eng._spec_proposed == 0  # spec path never fired


def test_mixed_penalized_batch_keeps_speculating():
    """VERDICT (round-2 item 5): one penalized request must NOT drop the
    whole batch off the speculative path — clean slots keep speculating
    (per-slot enable mask) while the penalized slot advances one normally-
    sampled, penalty-correct token per dispatch.  Outputs of BOTH must
    match their no-draft baselines (greedy byte-exactness)."""
    cfg = get_config("tiny")

    import jax
    params = tf.init_params(cfg, jax.random.PRNGKey(0))

    def run(draft):
        ecfg = EngineConfig(model="tiny", num_slots=4, max_cache_len=64,
                            prefill_buckets=(16, 32), steps_per_dispatch=4,
                            draft_model=draft, draft_len=4,
                            prefix_cache_mb=0)
        # Self-draft = SHARED weights (acceptance ~100% for clean slots).
        eng = InferenceEngine(cfg, ecfg, ByteTokenizer(), params=params,
                              draft_params=params if draft else None,
                              draft_cfg=cfg if draft else None)
        pen = Request("pen", PROMPTS[0], SamplingParams(
            max_tokens=10, temperature=0.0, ignore_eos=True,
            frequency_penalty=1.0))
        clean = Request("clean", PROMPTS[1], SamplingParams(
            max_tokens=10, temperature=0.0, ignore_eos=True))
        eng.add_request(pen)
        eng.add_request(clean)
        _drive(eng)
        return _collect(pen)[0], _collect(clean)[0], eng

    base_pen, base_clean, _ = run(None)
    spec_pen, spec_clean, eng = run("tiny")  # self-draft: accepts everything
    assert spec_clean == base_clean
    assert spec_pen == base_pen
    # Speculation actually ran for the clean slot despite the penalized one.
    assert eng._spec_proposed > 0
    assert eng._spec_accepted > 0


def test_mixed_logprob_batch_keeps_speculating():
    """A logprob-bearing request rides the spec dispatch disabled: it gets
    one token + logprob entry per dispatch while clean slots speculate."""
    cfg = get_config("tiny")
    ecfg = EngineConfig(model="tiny", num_slots=4, max_cache_len=64,
                        prefill_buckets=(16, 32), steps_per_dispatch=4,
                        draft_model="tiny", draft_len=4, prefix_cache_mb=0)
    eng = InferenceEngine(cfg, ecfg, ByteTokenizer())
    lp_req = Request("lp", PROMPTS[0], SamplingParams(
        max_tokens=6, temperature=0.0, ignore_eos=True, logprobs=2))
    clean = Request("clean", PROMPTS[1], SamplingParams(
        max_tokens=10, temperature=0.0, ignore_eos=True))
    eng.add_request(lp_req)
    eng.add_request(clean)
    _drive(eng)
    ids, lps = [], []
    while True:
        out = lp_req.outputs.get(timeout=60)
        ids.extend(out.token_ids)
        if out.logprobs:
            lps.extend(out.logprobs)
        if out.finished:
            break
    clean_ids, _ = _collect(clean)
    assert len(ids) == 6 and len(clean_ids) == 10
    assert eng._spec_proposed > 0
    # Full logprob stream for the disabled slot: one entry per token, each
    # a (chosen_logprob <= 0, top list) pair.
    assert len(lps) == 6
    assert all(entry[0] <= 0 and len(entry[1]) == 2 for entry in lps)


# ---------------------------------------------------------------------------
# Paged target cache + speculative decoding (the two production defaults
# together — previously mutually exclusive)
# ---------------------------------------------------------------------------


def _run_layout(kv_layout, prompts, draft_model, max_tokens=20,
                temperature=0.0, seed=None, sequential=False):
    cfg = get_config("tiny")
    ecfg = EngineConfig(model="tiny", num_slots=4, max_cache_len=64,
                        prefill_buckets=(16, 32), steps_per_dispatch=4,
                        prefill_chunk=16, kv_layout=kv_layout,
                        draft_model=draft_model, draft_len=4)
    eng = InferenceEngine(cfg, ecfg, ByteTokenizer())
    reqs = [Request(f"r{i}", p, SamplingParams(
        max_tokens=max_tokens, temperature=temperature, seed=seed,
        ignore_eos=True)) for i, p in enumerate(prompts)]
    if sequential:
        # One at a time: the second request's prefix lookup then sees the
        # first's pages in the digest index (deterministic hit).
        outs = []
        for r in reqs:
            eng.add_request(r)
            _drive(eng, n_steps=600)
            outs.append(_collect(r)[0])
        return outs, eng
    for r in reqs:
        eng.add_request(r)
    _drive(eng, n_steps=600)
    return [_collect(r)[0] for r in reqs], eng


def test_paged_spec_greedy_exactness():
    """Paged target + spec decode == slot target-only greedy, with verify
    blocks crossing page boundaries (page 16, 20 generated tokens) and the
    spec path actually firing."""
    base, _ = _run_layout("slot", PROMPTS, None)
    spec, eng = _run_layout("paged", PROMPTS, "tiny-gqa")
    assert spec == base
    assert eng._paged          # the layout actually resolved to paged
    assert eng._spec_proposed > 0
    # All request pages released after finish (no leak through the spec
    # write path); only index-retained prefix pages hold refs.
    assert eng._alloc.free_pages == (
        eng._alloc.num_pages - eng._alloc.retained_pages)


def test_paged_spec_prefix_sharing_stays_clean():
    """A shared prefix page must survive a sibling's speculative decode:
    the verify block writes land only in slot-owned tail pages."""
    shared = list(range(3, 23))           # 20 tokens -> one full page of 16
    prompts = [shared + [30], shared + [40]]
    base, _ = _run_layout("slot", prompts, None, max_tokens=12,
                          sequential=True)
    spec, eng = _run_layout("paged", prompts, "tiny-gqa", max_tokens=12,
                            sequential=True)
    assert spec == base
    assert eng._alloc.hit_tokens > 0      # the second prompt reused pages
    assert eng._spec_proposed > 0


def test_paged_spec_sampled_deterministic():
    """Sampled requests through paged+spec: valid tokens, deterministic
    per seed, and identical to the slot layout (same kernels, same keys)."""
    out1, eng = _run_layout("paged", PROMPTS[:2], "tiny-gqa",
                            temperature=0.8, seed=11)
    assert eng._spec_proposed > 0
    cfg = get_config("tiny")
    assert all(len(o) == 20 for o in out1)
    assert all(0 <= t < cfg.vocab_size for o in out1 for t in o)
    out2, _ = _run_layout("paged", PROMPTS[:2], "tiny-gqa",
                          temperature=0.8, seed=11)
    assert out2 == out1
    slot_out, _ = _run_layout("slot", PROMPTS[:2], "tiny-gqa",
                              temperature=0.8, seed=11)
    assert slot_out == out1
