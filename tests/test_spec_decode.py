"""Speculative decoding: draft proposes, the target verifies the block as
ragged q_len=draft_len rows of the MIXED dispatch (one program per
iteration serves decode feeds + prefill chunks + spec verify).

The load-bearing invariants:
- GREEDY speculative output is IDENTICAL to target-only output on the
  same (paged/mixed) engine — the draft only changes how many tokens land
  per dispatch, never which tokens — at pipeline depths 0 AND 2, with
  guided requests active in the same batch.
- Sampled output is exact in DISTRIBUTION
  (test_speculative_accept_distribution_exact) and deterministic per seed.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from arks_tpu.engine import EngineConfig, InferenceEngine, Request, SamplingParams
from arks_tpu.engine.tokenizer import ByteTokenizer
from arks_tpu.models import get_config, transformer as tf


def _drive(engine, n_steps=600):
    for _ in range(n_steps):
        engine.step(block_s=0.01)
        if (engine.num_running == 0 and engine._queue.empty()
                and not engine._prefilling
                and not engine._awaiting_guide):
            break


def _collect(req, timeout=60):
    ids, fin = [], None
    while True:
        out = req.outputs.get(timeout=timeout)
        ids.extend(out.token_ids)
        if out.finished:
            return ids, out


def _mk_engine(draft_model, depth=0, draft_len=4, shared_params=None,
               monkeypatch=None, **kw):
    """Spec engines require the mixed scheduler (paged + chunked prefill);
    baselines run the SAME engine shape without a draft so exactness
    comparisons are apples-to-apples."""
    if monkeypatch is not None:
        monkeypatch.setenv("ARKS_PIPELINE_DEPTH", str(depth))
    cfg = get_config("tiny")
    defaults = dict(model="tiny", num_slots=4, max_cache_len=64,
                    prefill_buckets=(16, 32), steps_per_dispatch=4,
                    prefill_chunk=16, kv_layout="paged",
                    draft_model=draft_model, draft_len=draft_len,
                    prefix_cache_mb=0)
    defaults.update(kw)
    ecfg = EngineConfig(**defaults)
    ekw = {}
    if shared_params is not None:
        ekw["params"] = shared_params
        if draft_model:
            ekw["draft_params"] = shared_params
            ekw["draft_cfg"] = cfg
    eng = InferenceEngine(cfg, ecfg, ByteTokenizer(), **ekw)
    if depth:
        assert eng._pipe_warm_wait(300) == "ready", eng._pipe_warm_state
    return cfg, eng


def _run(draft_model, prompts, max_tokens=12, temperature=0.0, seed=None,
         draft_len=4, depth=0, shared_params=None, monkeypatch=None, **kw):
    cfg, eng = _mk_engine(draft_model, depth=depth, draft_len=draft_len,
                          shared_params=shared_params,
                          monkeypatch=monkeypatch, **kw)
    reqs = [Request(f"r{i}", p, SamplingParams(
        max_tokens=max_tokens, temperature=temperature, seed=seed,
        ignore_eos=True)) for i, p in enumerate(prompts)]
    for r in reqs:
        eng.add_request(r)
    _drive(eng)
    return [_collect(r)[0] for r in reqs], eng


PROMPTS = [[5, 6, 7, 8, 9], [20, 21, 22], [3] * 18]


def test_greedy_exactness_vs_baseline():
    """Draft ("tiny-gqa", a DIFFERENT model) -> imperfect acceptance, but
    byte-identical greedy output vs the target-only mixed engine."""
    base, beng = _run(None, PROMPTS)
    assert beng._mixed
    spec, eng = _run("tiny-gqa", PROMPTS)
    assert spec == base
    # The spec path actually ran inside the mixed dispatch.
    assert eng._spec_proposed > 0
    assert eng.resolved_config["spec_mixed"] == "true"
    text = eng.metrics.registry.render()
    assert "spec_decode_acceptance_rate" in text
    assert "spec_decode_accepted_length" in text


def test_self_draft_accepts_everything():
    """Draft sharing the target's WEIGHTS: every proposal matches, so each
    dispatch lands the full draft block and acceptance is ~100%."""
    cfg = get_config("tiny")
    params = tf.init_params(cfg, jax.random.PRNGKey(0))
    base, _ = _run(None, PROMPTS[:1], shared_params=params)
    spec, eng = _run("tiny", PROMPTS[:1], shared_params=params)
    assert spec == base
    assert eng._spec_accepted == eng._spec_proposed > 0


def test_sampled_requests_ride_spec_path():
    """temperature > 0 slots take the rejection-sampled spec path: valid
    tokens, deterministic per seed, proposals accounted."""
    cfg = get_config("tiny")
    out1, eng = _run("tiny-gqa", PROMPTS[:1], temperature=0.8, seed=3)
    assert eng._spec_proposed > 0  # the spec path DID fire
    assert len(out1[0]) == 12
    assert all(0 <= t < cfg.vocab_size for t in out1[0])
    # Same seed, same engine shape -> same token stream.
    out2, _ = _run("tiny-gqa", PROMPTS[:1], temperature=0.8, seed=3)
    assert out2 == out1


@pytest.mark.parametrize("temperature,seed", [(0.0, None), (0.8, 7)])
def test_pipeline_depth_parity(monkeypatch, temperature, seed):
    """THE tentpole gate: spec streams are byte-identical at pipeline
    depths 0 and 2 (greedy AND seeded-sampled) — the spec_pipe program
    threads accepted-length/last-token state on device with the same
    kernel math as the fresh-entry spec-mixed program."""
    cfg = get_config("tiny")
    params = tf.init_params(cfg, jax.random.PRNGKey(0))
    d0, e0 = _run("tiny", PROMPTS, max_tokens=20, temperature=temperature,
                  seed=seed, depth=0, shared_params=params,
                  monkeypatch=monkeypatch)
    d2, e2 = _run("tiny", PROMPTS, max_tokens=20, temperature=temperature,
                  seed=seed, depth=2, shared_params=params,
                  monkeypatch=monkeypatch)
    assert d0 == d2
    assert e0._spec_proposed > 0 and e2._spec_proposed > 0
    # Depth 2 actually pipelined (occupancy histogram advanced).
    assert e2.metrics.pipeline_depth_occupancy._data


def test_guided_requests_speculate(monkeypatch):
    """Guided x spec compose: a guided request rides the spec path
    ENABLED (verify-aware DFA advancement), its stream byte-identical to
    the target-only guided baseline under greedy at depths 0 and 2, with
    an unguided request sharing the batch."""
    import re
    tok = ByteTokenizer()
    cfg = get_config("tiny")
    params = tf.init_params(cfg, jax.random.PRNGKey(0))

    def run(draft, depth):
        _, eng = _mk_engine(draft, depth=depth, shared_params=params,
                            monkeypatch=monkeypatch, max_cache_len=96)
        g = Request("g", tok.encode("zz"), SamplingParams(
            max_tokens=40, temperature=0.0, guide=("regex", r"ab+a")))
        plain = Request("p", [5, 6, 7], SamplingParams(
            max_tokens=20, temperature=0.0, ignore_eos=True))
        eng.add_request(g)
        eng.add_request(plain)
        _drive(eng, n_steps=1500)
        gids, gfin = _collect(g)
        pids, _ = _collect(plain)
        return gids, gfin.finish_reason, pids, eng

    g0, r0, p0, _ = run(None, 0)
    assert re.fullmatch(r"ab+a", tok.decode(g0)) and r0 == "stop"
    g1, r1, p1, eng1 = run("tiny", 0)
    assert (g1, r1, p1) == (g0, r0, p0)
    # The guided lane was spec-ENABLED and accepted drafts (self-draft).
    assert eng1._spec_accepted > 0
    g2, r2, p2, _ = run("tiny", 2)
    assert (g2, r2, p2) == (g0, r0, p0)


def test_guided_sampled_spec_respects_grammar():
    """Sampled guided requests through the spec path: grammar-valid and
    deterministic per seed (the per-position DFA mask keeps the emitted
    distribution exactly the engine's guided sampling dist)."""
    import re
    tok = ByteTokenizer()
    cfg = get_config("tiny")
    params = tf.init_params(cfg, jax.random.PRNGKey(0))

    def run():
        _, eng = _mk_engine("tiny", shared_params=params, max_cache_len=96)
        g = Request("g", tok.encode("zz"), SamplingParams(
            max_tokens=40, temperature=0.9, seed=11,
            guide=("regex", r"ab+a")))
        eng.add_request(g)
        _drive(eng, n_steps=1500)
        return _collect(g)[0]

    out1, out2 = run(), run()
    assert out1 == out2
    assert re.fullmatch(r"ab+a", tok.decode(out1))


def test_speculative_accept_distribution_exact():
    """Brute-force the rejection kernel: over many trials the emitted first
    token's empirical distribution matches the target's effective sampling
    distribution (the Leviathan guarantee), for a draft that is WRONG."""
    from arks_tpu.engine import sampler as sm

    V, K, N = 12, 3, 4000
    rng = np.random.default_rng(0)
    t_logits = jnp.asarray(rng.standard_normal((1, K, V)), jnp.float32)
    d_logits = jnp.asarray(rng.standard_normal((1, V)), jnp.float32)
    state = sm.init_sampling_state(1, seed=0, vocab_size=V)._replace(
        temperature=jnp.asarray([1.0]))

    @jax.jit
    def one_trial(key):
        keys = key[None]
        tok, q, qp, qi, keys = sm.draft_sample(d_logits, state, keys)
        # Second draft step from the same (stale) draft dist — a crude but
        # legal proposer.
        tok2, q2, qp2, qi2, keys = sm.draft_sample(d_logits, state, keys)
        drafts = jnp.stack([tok, tok2], axis=1)          # [1, K-1]
        q_sel = jnp.stack([q, q2], axis=1)
        q_probs = jnp.stack([qp, qp2], axis=1)
        q_idx = jnp.stack([qi, qi2], axis=1)
        out, counts, _, _ = sm.speculative_accept(
            drafts, q_sel, q_probs, q_idx, t_logits, state, keys)
        return out[0, 0]  # the FIRST emitted token

    keys = jax.random.split(jax.random.PRNGKey(42), N)
    toks = np.asarray(jax.vmap(one_trial)(keys))
    emp = np.bincount(toks, minlength=V) / N
    expected = np.asarray(sm.filtered_probs(t_logits[:, 0], state)[0][0])
    # Map window order back to vocab order.
    idx = np.asarray(sm.filtered_probs(t_logits[:, 0], state)[1][0])
    exp_vocab = np.zeros(V)
    exp_vocab[idx] = expected
    tv = 0.5 * np.abs(emp - exp_vocab).sum()
    assert tv < 0.05, f"total variation {tv:.3f} vs target dist"


def test_speculative_accept_guided_distribution_exact():
    """Guided variant of the kernel brute-force: with a DFA masking half
    the vocab at every state, the emitted first token matches the MASKED
    target distribution — even though the draft proposes from the
    unmasked one (forbidden proposals are always rejected; the residual
    resamples legally)."""
    from arks_tpu.engine import sampler as sm

    V, K, N = 12, 3, 4000
    rng = np.random.default_rng(1)
    t_logits = jnp.asarray(rng.standard_normal((1, K, V)), jnp.float32)
    d_logits = jnp.asarray(rng.standard_normal((1, V)), jnp.float32)
    # One guide, one state: tokens with class 0 allowed (self-loop to row
    # 0), class 1 dead.  Even token ids are forbidden.
    class_ids = jnp.asarray(
        [[1 if v % 2 == 0 else 0 for v in range(V)]], jnp.int32)  # [G, V]
    trans = jnp.asarray([[0, -1]], jnp.int32)                     # [R, C]
    gtables = (class_ids, trans)
    state = sm.init_sampling_state(1, seed=0, vocab_size=V)._replace(
        temperature=jnp.asarray([1.0]),
        guide=jnp.asarray([0], jnp.int32))

    @jax.jit
    def one_trial(key):
        keys = key[None]
        tok, q, qp, qi, keys = sm.draft_sample(d_logits, state, keys)
        tok2, q2, qp2, qi2, keys = sm.draft_sample(d_logits, state, keys)
        drafts = jnp.stack([tok, tok2], axis=1)
        q_sel = jnp.stack([q, q2], axis=1)
        q_probs = jnp.stack([qp, qp2], axis=1)
        q_idx = jnp.stack([qi, qi2], axis=1)
        out, counts, _, grow = sm.speculative_accept(
            drafts, q_sel, q_probs, q_idx, t_logits, state, keys,
            enable=jnp.asarray([True]), guide_tables=gtables)
        return out[0, 0]

    keys = jax.random.split(jax.random.PRNGKey(43), N)
    toks = np.asarray(jax.vmap(one_trial)(keys))
    assert (toks % 2 == 1).all(), "grammar-forbidden token emitted"
    emp = np.bincount(toks, minlength=V) / N
    masked = np.asarray(t_logits[0, 0])
    masked = np.where(np.arange(V) % 2 == 0, -1e30, masked)
    mstate = state._replace(guide=jnp.asarray([-1], jnp.int32))
    expected = np.asarray(sm.filtered_probs(
        jnp.asarray(masked)[None], mstate)[0][0])
    idx = np.asarray(sm.filtered_probs(
        jnp.asarray(masked)[None], mstate)[1][0])
    exp_vocab = np.zeros(V)
    exp_vocab[idx] = expected
    tv = 0.5 * np.abs(emp - exp_vocab).sum()
    assert tv < 0.05, f"total variation {tv:.3f} vs masked target dist"


def test_stop_token_mid_block():
    """A stop token inside an accepted block truncates the output there."""
    base, _ = _run(None, PROMPTS[:1], max_tokens=40)
    stop_tok = base[0][5]
    cfg, eng = _mk_engine("tiny", num_slots=2)
    req = Request("s", PROMPTS[0], SamplingParams(
        max_tokens=40, temperature=0.0, ignore_eos=True,
        stop_token_ids=[stop_tok]))
    eng.add_request(req)
    _drive(eng)
    ids, fin = _collect(req)
    assert fin.finish_reason == "stop"
    assert ids == base[0][:5]  # truncated before the stop token


def test_verify_step_matches_sequential_decode():
    """tf.verify_step stays as the multi-token scoring ORACLE (the serving
    path now rides mixed_step; tests/test_paged_attention.py closes the
    loop between the two)."""
    cfg = get_config("tiny")
    params = tf.init_params(cfg, jax.random.PRNGKey(0), jnp.float32)
    B, K, L0 = 2, 4, 9
    cache_a = tf.init_cache(cfg, B, 32, jnp.float32)
    cache_b = tf.init_cache(cfg, B, 32, jnp.float32)
    toks0 = jax.random.randint(jax.random.PRNGKey(1), (1, L0), 0, cfg.vocab_size)
    _, ks, vs = tf.prefill(params, cfg, toks0, jnp.asarray([L0], jnp.int32))
    for s in range(B):
        cache_a = tf.insert(cache_a, ks, vs, jnp.asarray(s))
        cache_b = tf.insert(cache_b, ks, vs, jnp.asarray(s))
    block = jax.random.randint(jax.random.PRNGKey(2), (B, K), 0, cfg.vocab_size)
    lengths = jnp.full((B,), L0, jnp.int32)
    seq = []
    ca, ln = cache_a, lengths
    for i in range(K):
        lg, ca = tf.decode_step(params, cfg, ca, block[:, i], ln)
        seq.append(lg)
        ln = ln + 1
    seq = jnp.stack(seq, axis=1)
    ver, cb = tf.verify_step(params, cfg, cache_b, block, lengths)
    np.testing.assert_allclose(np.asarray(seq), np.asarray(ver), atol=1e-5)
    np.testing.assert_allclose(np.asarray(ca.k), np.asarray(cb.k), atol=1e-6)


def test_spec_decode_config_validation():
    """The new compatibility surface: draft_len >= 2 and pp/dp exclusion
    as before, plus the mixed-scheduler requirement — a slot layout or
    ARKS_MIXED_STEP=0 cannot host a draft model (there is no legacy spec
    scheduler to fall back to anymore)."""
    cfg = get_config("tiny")
    with pytest.raises(ValueError, match="draft_len"):
        InferenceEngine(cfg, EngineConfig(model="tiny", draft_model="tiny",
                                          draft_len=1), ByteTokenizer())
    with pytest.raises(ValueError, match="pipeline_parallel"):
        InferenceEngine(cfg, EngineConfig(model="tiny", draft_model="tiny",
                                          pipeline_parallel=2),
                        ByteTokenizer())
    with pytest.raises(ValueError, match="mixed scheduler"):
        InferenceEngine(cfg, EngineConfig(model="tiny", draft_model="tiny",
                                          kv_layout="slot",
                                          prefill_chunk=16),
                        ByteTokenizer())
    with pytest.raises(ValueError, match="mixed scheduler"):
        InferenceEngine(cfg, EngineConfig(model="tiny", draft_model="tiny",
                                          prefill_chunk=None,
                                          kv_layout="paged"),
                        ByteTokenizer())


def test_spec_mixed_env_off_rejected(monkeypatch):
    monkeypatch.setenv("ARKS_MIXED_STEP", "0")
    cfg = get_config("tiny")
    with pytest.raises(ValueError, match="mixed scheduler"):
        InferenceEngine(cfg, EngineConfig(model="tiny", draft_model="tiny",
                                          kv_layout="paged",
                                          prefill_chunk=16),
                        ByteTokenizer())


def test_auto_layout_resolves_paged_for_draft_engines():
    """kv_layout=auto resolves to paged for draft engines even on CPU —
    speculation requires the mixed scheduler, and "auto" must not turn a
    valid spec config into an init error off-TPU."""
    _, eng = _mk_engine("tiny-gqa", kv_layout="auto")
    assert eng._paged and eng._mixed
    _, base = _mk_engine(None, kv_layout="auto")
    assert not base._paged  # non-draft CPU engines keep the slot layout


def test_mixed_batch_greedy_exactness():
    """Greedy and sampled slots share spec dispatches (rejection kernel
    handles both); the greedy request's output must STILL be byte-identical
    to the target-only baseline."""
    base, _ = _run(None, [PROMPTS[0]], max_tokens=20)
    cfg, eng = _mk_engine("tiny-gqa", num_slots=2)
    greedy = Request("g", PROMPTS[0], SamplingParams(max_tokens=20,
                                                     temperature=0.0,
                                                     ignore_eos=True))
    sampled = Request("s", PROMPTS[1], SamplingParams(max_tokens=20,
                                                      temperature=0.9,
                                                      seed=1,
                                                      ignore_eos=True))
    eng.add_request(greedy)
    eng.add_request(sampled)
    _drive(eng)
    g_ids, _ = _collect(greedy)
    s_ids, _ = _collect(sampled)
    assert eng._spec_proposed > 0      # mixed batch rode the spec path
    assert g_ids == base[0]            # greedy exactness survives company
    assert len(s_ids) == 20
    assert all(0 <= t < cfg.vocab_size for t in s_ids)


def test_long_prompt_skips_draft_prefill():
    """Prompts beyond the one-shot buckets skip the (monolithic) draft
    prefill; the lane rides the dispatch permanently DISABLED — still
    correct, only the draft speedup is forfeited."""
    cfg, eng = _mk_engine("tiny-gqa", num_slots=2, prefill_buckets=(16,))
    long_prompt = [int(x) % cfg.vocab_size for x in range(3, 45)]  # 42 > 16
    r = Request("l", long_prompt, SamplingParams(max_tokens=4,
                                                 temperature=0.0,
                                                 ignore_eos=True))
    eng.add_request(r)
    _drive(eng)
    ids, fin = _collect(r)
    assert fin.num_prompt_tokens == 42 and len(ids) == 4
    assert eng._spec_proposed == 0  # slot never draft-synced


def test_penalized_requests_ride_disabled():
    """Presence/frequency penalties evolve per-token counts, which the spec
    kernel doesn't model within a block — penalized slots ride the spec
    dispatch DISABLED (one penalty-correct token per dispatch), matching
    the no-draft baseline byte-for-byte."""
    base, _ = _run(None, PROMPTS[:1], max_tokens=10, temperature=0.0)
    cfg, eng = _mk_engine("tiny-gqa", num_slots=2)
    req = Request("pen", PROMPTS[0], SamplingParams(
        max_tokens=10, temperature=0.0, ignore_eos=True,
        frequency_penalty=1.0))
    eng.add_request(req)
    _drive(eng)
    ids, _ = _collect(req)
    assert len(ids) == 10
    assert eng._spec_proposed == 0  # the only slot was disabled

    # And the penalized stream matches a penalized no-draft baseline.
    _, beng = _mk_engine(None, num_slots=2)
    breq = Request("pen", PROMPTS[0], SamplingParams(
        max_tokens=10, temperature=0.0, ignore_eos=True,
        frequency_penalty=1.0))
    beng.add_request(breq)
    _drive(beng)
    bids, _ = _collect(breq)
    assert ids == bids


def test_mixed_penalized_batch_keeps_speculating():
    """One penalized request must NOT drop the whole batch off the
    speculative path — clean slots keep speculating (per-slot enable mask)
    while the penalized slot advances one normally-sampled,
    penalty-correct token per dispatch.  Outputs of BOTH must match their
    no-draft baselines (greedy byte-exactness)."""
    cfg = get_config("tiny")
    params = tf.init_params(cfg, jax.random.PRNGKey(0))

    def run(draft):
        _, eng = _mk_engine(draft, shared_params=params)
        pen = Request("pen", PROMPTS[0], SamplingParams(
            max_tokens=10, temperature=0.0, ignore_eos=True,
            frequency_penalty=1.0))
        clean = Request("clean", PROMPTS[1], SamplingParams(
            max_tokens=10, temperature=0.0, ignore_eos=True))
        eng.add_request(pen)
        eng.add_request(clean)
        _drive(eng)
        return _collect(pen)[0], _collect(clean)[0], eng

    base_pen, base_clean, _ = run(None)
    spec_pen, spec_clean, eng = run("tiny")  # self-draft: accepts everything
    assert spec_clean == base_clean
    assert spec_pen == base_pen
    # Speculation actually ran for the clean slot despite the penalized one.
    assert eng._spec_proposed > 0
    assert eng._spec_accepted > 0


def test_mixed_logprob_batch_keeps_speculating():
    """A logprob-bearing request rides the spec dispatch disabled: it gets
    one token + logprob entry per dispatch while clean slots speculate."""
    cfg, eng = _mk_engine("tiny")
    lp_req = Request("lp", PROMPTS[0], SamplingParams(
        max_tokens=6, temperature=0.0, ignore_eos=True, logprobs=2))
    clean = Request("clean", PROMPTS[1], SamplingParams(
        max_tokens=10, temperature=0.0, ignore_eos=True))
    eng.add_request(lp_req)
    eng.add_request(clean)
    _drive(eng)
    ids, lps = [], []
    while True:
        out = lp_req.outputs.get(timeout=60)
        ids.extend(out.token_ids)
        if out.logprobs:
            lps.extend(out.logprobs)
        if out.finished:
            break
    clean_ids, _ = _collect(clean)
    assert len(ids) == 6 and len(clean_ids) == 10
    assert eng._spec_proposed > 0
    # Full logprob stream for the disabled slot: one entry per token, each
    # a (chosen_logprob <= 0, top list) pair.
    assert len(lps) == 6
    assert all(entry[0] <= 0 and len(entry[1]) == 2 for entry in lps)


# ---------------------------------------------------------------------------
# Paged mechanics under speculative decoding (prefix sharing, page release,
# page-boundary-crossing verify blocks)
# ---------------------------------------------------------------------------


def test_paged_spec_page_hygiene():
    """All request pages released after finish (no leak through the spec
    write path); verify blocks cross page boundaries (page 16, 20
    generated tokens) and the spec path actually fires."""
    base, _ = _run(None, PROMPTS, max_tokens=20)
    spec, eng = _run("tiny-gqa", PROMPTS, max_tokens=20)
    assert spec == base
    assert eng._paged
    assert eng._spec_proposed > 0
    assert eng._alloc.free_pages == (
        eng._alloc.num_pages - eng._alloc.retained_pages)


def test_paged_spec_prefix_sharing_stays_clean():
    """A shared prefix page must survive a sibling's speculative decode:
    the verify block writes land only in slot-owned tail pages."""
    shared = list(range(3, 23))           # 20 tokens -> one full page of 16
    prompts = [shared + [30], shared + [40]]

    def run_sequential(draft):
        cfg, eng = _mk_engine(draft, prefix_cache_mb=256)
        outs = []
        for i, p in enumerate(prompts):
            r = Request(f"r{i}", p, SamplingParams(
                max_tokens=12, temperature=0.0, ignore_eos=True))
            eng.add_request(r)
            _drive(eng)
            outs.append(_collect(r)[0])
        return outs, eng

    base, _ = run_sequential(None)
    spec, eng = run_sequential("tiny-gqa")
    assert spec == base
    assert eng._alloc.hit_tokens > 0      # the second prompt reused pages
    assert eng._spec_proposed > 0
