"""Tier-2 disk prefix store: warm prefixes survive an engine restart.

The acceptance surface of the fleet-prefix PR's persistence half:

- an engine relaunched on the same ``ARKS_PREFIX_DISK_DIR`` serves a
  previously-warm prefix with ZERO re-prefilled full-page tokens (the
  admission parks in the fetch path, the disk blocks stage into tier 1,
  and the ordinary restore path scatters them back);
- the round trip is bit-exact for int8/int4-packed blocks with scales
  (blocks are raw pool-native bytes, so spill -> restore cannot drift);
- blocks written under a different pool layout epoch are rejected, not
  served (manifest wipe on boot + per-file epoch check on read);
- a corrupt/truncated file is swallowed, deleted, and counted — never
  returned to a restore.
"""

import os

import numpy as np
import pytest

from arks_tpu.engine import (EngineConfig, InferenceEngine, Request,
                             SamplingParams)
from arks_tpu.engine import kv_transfer
from arks_tpu.engine.paged import chain_digests
from arks_tpu.engine.prefix_cache import DiskPrefixTier
from arks_tpu.engine.tokenizer import ByteTokenizer
from arks_tpu.models import get_config


def _mk(monkeypatch, ddir, host_mb="64", disk_mb="8", **kw):
    monkeypatch.setenv("ARKS_PIPELINE_DEPTH", "0")
    monkeypatch.setenv("ARKS_MIXED_STEP", "auto")
    monkeypatch.setenv("ARKS_PREFIX_HOST_MB", host_mb)
    monkeypatch.setenv("ARKS_PREFIX_DISK_MB", disk_mb)
    monkeypatch.setenv("ARKS_PREFIX_DISK_DIR", str(ddir))
    cfg = get_config("tiny")
    defaults = dict(model="tiny", num_slots=2, max_cache_len=64,
                    prefill_buckets=(8, 16, 32), steps_per_dispatch=4,
                    prefill_chunk=16, kv_layout="paged", prefix_cache_mb=0)
    defaults.update(kw)
    return cfg, InferenceEngine(cfg, EngineConfig(**defaults),
                                ByteTokenizer())


def _drive(eng, n_steps=2000):
    """The engine thread's step/recover contract, synchronously — with
    the fetch park and the disk spill queue in the liveness condition."""
    for _ in range(n_steps):
        try:
            eng.step(block_s=0.01)
        except Exception as e:  # noqa: BLE001 — routed like _run_loop
            eng._recover_from_fault(e)
        if (eng.num_running == 0 and eng._queue.empty()
                and not eng._prefilling and not eng._awaiting_fetch
                and not eng._awaiting_restore and eng.state == "serving"):
            break


def _collect(req, timeout=120):
    ids, fin = [], None
    while True:
        out = req.outputs.get(timeout=timeout)
        ids.extend(out.token_ids)
        if out.finished:
            fin = out
            break
    return ids, fin


def _run_one(eng, rid, ids, max_tokens=4):
    req = Request(rid, ids, SamplingParams(
        max_tokens=max_tokens, temperature=0.0, ignore_eos=True))
    eng.add_request(req)
    _drive(eng)
    return _collect(req)


def _block(rng, dtype, with_scales, page=16, heads=8, dim=8, layers=2):
    shape = (layers, heads, page, dim)
    if np.issubdtype(dtype, np.integer):
        info = np.iinfo(dtype)
        k = rng.integers(info.min, info.max + 1, size=shape, dtype=dtype)
        v = rng.integers(info.min, info.max + 1, size=shape, dtype=dtype)
    else:
        k = rng.standard_normal(shape).astype(dtype)
        v = rng.standard_normal(shape).astype(dtype)
    blk = {"k": k, "v": v}
    if with_scales:
        blk["k_scale"] = rng.standard_normal(
            (layers, heads, page, 1)).astype(np.float32)
        blk["v_scale"] = rng.standard_normal(
            (layers, heads, page, 1)).astype(np.float32)
    return blk


# --------------------------------------------------- engine restart


def test_restart_serves_warm_prefix_from_disk(monkeypatch, tmp_path):
    """Kill/relaunch on the same ARKS_PREFIX_DISK_DIR: the relaunched
    engine serves the warm prompt byte-identically with zero re-prefilled
    full-page tokens — every full page comes back through the disk fetch
    + tier-1 restore path, and only the tail is chunk-prefilled."""
    ddir = tmp_path / "store"
    cfg, a = _mk(monkeypatch, ddir)
    warm = [int(x) % cfg.vocab_size for x in range(3, 36)]  # 2 pages + tail
    base = _run_one(a, "w1", warm)
    a_chunk = a.metrics.mixed_chunk_tokens_total.total()
    assert base[1].finish_reason == "length"
    a.stop()  # graceful stop publishes warm state into the disk store

    digests = chain_digests(warm, 16, 2)
    files = {f.name for f in ddir.iterdir()}
    assert DiskPrefixTier.MANIFEST in files
    for d in digests:
        assert d.hex() + DiskPrefixTier.SUFFIX in files, \
            "warm block missing from the disk store after stop()"

    cfg, b = _mk(monkeypatch, ddir)
    assert b._disk.num_blocks >= 2, "boot scan did not adopt the blocks"
    got = _run_one(b, "w2", warm)
    try:
        assert got[0] == base[0], "stream diverged across the restart"
        assert got[1].finish_reason == base[1].finish_reason
        # Zero re-prefilled warm-prefix tokens: both full pages restored
        # from disk; the chunked path saw strictly less than one cold run.
        assert b.metrics.prefix_cache_hit_tokens_total.get(tier="disk") == 32
        assert b.metrics.prefix_peer_fetch_blocks_total.get(
            source="disk") == 2
        assert b.metrics.prefix_restore_blocks_total.total() >= 2
        assert b.metrics.mixed_chunk_tokens_total.total() < a_chunk
    finally:
        b.stop()


def test_restart_on_other_layout_epoch_rejects_stale_blocks(
        monkeypatch, tmp_path):
    """A directory written by engine A must never be served under a
    different pool layout.  Simulated by re-stamping the tier with a
    different epoch: boot wipes the stale files, and a stale-epoch file
    smuggled behind the manifest's back is rejected on read (defense in
    depth), not reinterpreted as pool bytes."""
    ddir = tmp_path / "store"
    rng = np.random.default_rng(0)
    t1 = DiskPrefixTier(16, 1 << 20, str(ddir), epoch="layout-A")
    d1 = b"\x01" * 20
    assert t1.put(d1, _block(rng, np.int8, True))

    # Relaunch under another layout: manifest mismatch wipes the store.
    t2 = DiskPrefixTier(16, 1 << 20, str(ddir), epoch="layout-B")
    assert not t2.has(d1)
    assert t2.get(d1) is None
    assert not list(ddir.glob("*" + DiskPrefixTier.SUFFIX))

    # Defense in depth: a layout-A file appearing under a layout-B
    # manifest (crashed writer from the previous layout) is adopted by
    # the boot scan but REJECTED on read and dropped.
    d2 = b"\x02" * 20
    buf = kv_transfer.pack_block(d2, "layout-A", _block(rng, np.int8, True))
    (ddir / (d2.hex() + DiskPrefixTier.SUFFIX)).write_bytes(buf)
    t3 = DiskPrefixTier(16, 1 << 20, str(ddir), epoch="layout-B")
    assert t3.has(d2)            # indexed by the scan...
    assert t3.get(d2) is None    # ...but never served
    assert not t3.has(d2)
    assert t3.corrupt_blocks == 1


# ------------------------------------------------ bit-exact round trip


@pytest.mark.parametrize("dtype,scales", [
    (np.int8, True),       # int8-quantized pool pages + f32 scales
    (np.uint8, True),      # int4-packed pages ride uint8 nibbles
    (np.float32, False),   # full-width pool
], ids=["int8", "int4-packed", "f32"])
def test_disk_round_trip_is_bit_exact(monkeypatch, tmp_path, dtype, scales):
    rng = np.random.default_rng(7)
    t = DiskPrefixTier(16, 1 << 20, str(tmp_path), epoch="e")
    blk = _block(rng, dtype, scales)
    dg = b"\x0a" * 20
    assert t.put(dg, blk)

    # Same process and a fresh adoption of the directory both serve the
    # exact bytes that went in.
    t2 = DiskPrefixTier(16, 1 << 20, str(tmp_path), epoch="e")
    for tier in (t, t2):
        out = tier.get(dg)
        assert set(out) == set(blk)
        for f in blk:
            assert out[f].dtype == blk[f].dtype
            assert out[f].shape == blk[f].shape
            assert out[f].tobytes() == blk[f].tobytes()


def test_corrupt_block_is_swallowed_and_dropped(tmp_path):
    rng = np.random.default_rng(3)
    t = DiskPrefixTier(16, 1 << 20, str(tmp_path), epoch="e")
    dg = b"\x0b" * 20
    assert t.put(dg, _block(rng, np.int8, True))
    path = tmp_path / (dg.hex() + DiskPrefixTier.SUFFIX)
    path.write_bytes(path.read_bytes()[:40])  # truncate mid-header

    assert t.get(dg) is None
    assert t.corrupt_blocks == 1
    assert not t.has(dg)
    assert not path.exists()


def test_eviction_honors_byte_budget_and_unlinks(tmp_path):
    rng = np.random.default_rng(5)
    t = DiskPrefixTier(16, 1 << 20, str(tmp_path), epoch="e")
    one = t  # size one block first to learn the budget unit
    b0 = _block(rng, np.int8, True)
    d0 = bytes([0]) * 20
    assert one.put(d0, b0)
    unit = t.bytes_used
    t.capacity = int(unit * 2.5)  # room for two blocks
    digs = [bytes([i + 1]) * 20 for i in range(3)]
    for d in digs:
        assert t.put(d, _block(rng, np.int8, True))
    assert t.num_blocks == 2
    assert t.evicted_blocks == 2
    assert t.bytes_used <= t.capacity
    # Evicted files are gone from disk, survivors still present.
    on_disk = {f.name for f in tmp_path.glob("*" + DiskPrefixTier.SUFFIX)}
    assert on_disk == {d.hex() + DiskPrefixTier.SUFFIX
                      for d in (digs[-2], digs[-1])}


def test_tmp_orphans_are_cleaned_on_boot(tmp_path):
    rng = np.random.default_rng(9)
    t = DiskPrefixTier(16, 1 << 20, str(tmp_path), epoch="e")
    t.put(b"\x0c" * 20, _block(rng, np.int8, True))
    orphan = tmp_path / ("deadbeef" + DiskPrefixTier.SUFFIX + ".123.tmp")
    orphan.write_bytes(b"torn write")
    t2 = DiskPrefixTier(16, 1 << 20, str(tmp_path), epoch="e")
    assert not orphan.exists()
    assert t2.num_blocks == 1


def test_disk_dir_defaults_under_tmpdir(monkeypatch, tmp_path):
    """ARKS_PREFIX_DISK_MB alone is enough to turn the tier on — the
    directory defaults under the system tempdir."""
    monkeypatch.setenv("TMPDIR", str(tmp_path))
    import tempfile
    tempfile.tempdir = None  # re-read TMPDIR
    try:
        monkeypatch.setenv("ARKS_PIPELINE_DEPTH", "0")
        monkeypatch.setenv("ARKS_MIXED_STEP", "auto")
        monkeypatch.setenv("ARKS_PREFIX_HOST_MB", "64")
        monkeypatch.setenv("ARKS_PREFIX_DISK_MB", "8")
        monkeypatch.delenv("ARKS_PREFIX_DISK_DIR", raising=False)
        cfg = get_config("tiny")
        eng = InferenceEngine(
            cfg, EngineConfig(model="tiny", num_slots=2, max_cache_len=64,
                              prefill_buckets=(8, 16, 32),
                              steps_per_dispatch=4, prefill_chunk=16,
                              kv_layout="paged", prefix_cache_mb=0),
            ByteTokenizer())
        try:
            assert eng._disk is not None
            assert eng._disk.dir.startswith(str(tmp_path))
            assert os.path.isdir(eng._disk.dir)
        finally:
            eng.stop()
    finally:
        tempfile.tempdir = None
