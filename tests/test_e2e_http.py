"""Cluster-e2e tier without a cluster: the live operator drives a fake
apiserver OVER REAL HTTP through the production KubeApi client — the wire
protocol (URL building, merge-patch content types, status subresource,
error mapping) is exercised end to end, the role the reference's Kind
suite plays (test/e2e/e2e_test.go:45-270)."""

import time

import pytest

from arks_tpu.control.k8s_client import ApiError, FakeApiServer, KubeApi
from arks_tpu.control.live import FINALIZER, GV, LiveOperator


def wait_for(predicate, timeout=30.0, interval=0.05):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        v = predicate()
        if v:
            return v
        time.sleep(interval)
    raise AssertionError("condition not met within timeout")


@pytest.fixture()
def http_world(tmp_path):
    srv = FakeApiServer()
    srv.start()
    api = KubeApi(srv.url)
    op = LiveOperator(api, models_root=str(tmp_path / "models"),
                      interval_s=0.1)
    op.start()
    yield api, srv
    op.stop()
    srv.stop()


def _cr(kind, name, spec, ns="default"):
    return {"apiVersion": GV, "kind": kind,
            "metadata": {"name": name, "namespace": ns}, "spec": spec}


def test_http_wire_roundtrip(http_world):
    """Client-level semantics over the real wire: create / get / list /
    merge-patch (incl. null-deletes and the status subresource) / replace /
    404 mapping."""
    api, _ = http_world
    api.create("apps/v1", "statefulsets", "ns1", {
        "metadata": {"name": "s1"}, "spec": {"replicas": 2, "extra": "x"}})
    obj = api.get("apps/v1", "statefulsets", "ns1", "s1")
    assert obj["spec"]["replicas"] == 2
    # Merge-patch: null deletes a key.
    api.patch("apps/v1", "statefulsets", "ns1", "s1",
              {"spec": {"extra": None, "replicas": 3}})
    obj = api.get("apps/v1", "statefulsets", "ns1", "s1")
    assert obj["spec"] == {"replicas": 3}
    # Status subresource only touches .status.
    api.patch("apps/v1", "statefulsets", "ns1", "s1",
              {"status": {"readyReplicas": 3}}, subresource="status")
    obj = api.get("apps/v1", "statefulsets", "ns1", "s1")
    assert obj["status"]["readyReplicas"] == 3 and obj["spec"]["replicas"] == 3
    # Replace (PUT) drops unspecified spec keys.
    obj["spec"] = {"replicas": 1}
    api.replace("apps/v1", "statefulsets", "ns1", "s1", obj)
    assert api.get("apps/v1", "statefulsets", "ns1", "s1")["spec"] == {"replicas": 1}
    # 404 mapping: get -> None, delete -> swallowed, create conflict -> 409.
    assert api.get("apps/v1", "statefulsets", "ns1", "nope") is None
    api.delete("apps/v1", "statefulsets", "ns1", "nope")
    try:
        api.create("apps/v1", "statefulsets", "ns1", {"metadata": {"name": "s1"}})
        raise AssertionError("expected 409")
    except ApiError as e:
        assert e.status == 409
    assert [o["metadata"]["name"]
            for o in api.list("apps/v1", "statefulsets", "ns1")] == ["s1"]


def test_http_operator_end_to_end(http_world):
    """Full loop over HTTP: CRs in -> owned StatefulSets/Services out,
    readiness back into CR status, finalizer-gated deletion."""
    api, _ = http_world
    api.create(GV, "arksmodels", "default",
               _cr("ArksModel", "m1", {"model": "org/m"}))
    api.create(GV, "arksapplications", "default", _cr(
        "ArksApplication", "webapp", {
            "replicas": 2, "size": 1, "runtime": "jax",
            "model": {"name": "m1"}, "servedModelName": "web-served",
            "modelConfig": "tiny",
        }))

    def sts_names():
        return sorted(s["metadata"]["name"]
                      for s in api.list("apps/v1", "statefulsets"))

    wait_for(lambda: sts_names() == ["arks-webapp-0", "arks-webapp-1"])
    app = api.get(GV, "arksapplications", "default", "webapp")
    assert FINALIZER in app["metadata"]["finalizers"]

    for n in sts_names():
        api.patch("apps/v1", "statefulsets", "default", n,
                  {"status": {"readyReplicas": 1}}, subresource="status")
    wait_for(lambda: (api.get(GV, "arksapplications", "default", "webapp")
                      .get("status", {}).get("phase")) == "Running")

    api.delete(GV, "arksapplications", "default", "webapp")
    wait_for(lambda: api.get(GV, "arksapplications", "default", "webapp") is None)
    assert sts_names() == []


def test_http_two_operators_leader_election_and_expiry_failover(tmp_path):
    """VERDICT acceptance (operator HA): TWO LiveOperators against the
    FakeApiServer over REAL HTTP — single-writer reconciliation (the
    standby ingests nothing), optimistic-concurrency Lease takeover through
    the wire's 409 mapping, and failover on lease EXPIRY when the leader
    dies without releasing."""
    from arks_tpu.control import resources as res
    from arks_tpu.control.leader import LeaderElector

    srv = FakeApiServer()
    srv.start()

    def mk(ident, lease_s):
        api = KubeApi(srv.url)
        elector = LeaderElector(api, namespace="arks-system",
                                identity=ident, lease_duration_s=lease_s,
                                retry_period_s=0.05)
        return LiveOperator(api, models_root=str(tmp_path / ident),
                            interval_s=0.1, leader_elector=elector,
                            exit_on_lost_lease=False)

    # 5s lease: long enough that suite-load starvation cannot steal
    # it mid-test, short enough that the expiry-failover phase stays
    # quick.
    a = mk("op-a", lease_s=5.0)
    b = mk("op-b", lease_s=5.0)
    client = KubeApi(srv.url)
    a.start()
    try:
        wait_for(lambda: a.is_leader)
        b.start()
        client.create(GV, "arksmodels", "default",
                      _cr("ArksModel", "m1", {"model": "org/m"}))
        client.create(GV, "arksapplications", "default", _cr(
            "ArksApplication", "app1", {
                "replicas": 1, "size": 1, "runtime": "jax",
                "model": {"name": "m1"}, "servedModelName": "served",
                "modelConfig": "tiny"}))
        wait_for(lambda: [s["metadata"]["name"] for s in client.list(
            "apps/v1", "statefulsets")] == ["arks-app1-0"])
        # Single writer: the standby's machinery never started, its store
        # is empty, and the lease names the leader.
        assert a.is_leader and not b.is_leader
        assert b.store.list(res.Application) == []
        lease = client.get("coordination.k8s.io/v1", "leases",
                           "arks-system", "e4ada7ad.arks.ai")
        assert lease["spec"]["holderIdentity"] == "op-a"

        # Crash the leader WITHOUT releasing (elector stops renewing):
        # the standby must take over only after expiry, via a
        # resourceVersion-fenced PUT over HTTP.
        a.elector.stop(release=False)
        a._stop_machinery()
        from arks_tpu.control.leader import _parse_rfc3339
        dead = client.get("coordination.k8s.io/v1", "leases",
                          "arks-system", "e4ada7ad.arks.ai")["spec"]
        expiry = (_parse_rfc3339(dead["renewTime"])
                  + dead["leaseDurationSeconds"])
        wait_for(lambda: b.is_leader, timeout=30.0)
        wait_for(lambda: b._machinery_started)
        lease = client.get("coordination.k8s.io/v1", "leases",
                           "arks-system", "e4ada7ad.arks.ai")
        assert lease["spec"]["holderIdentity"] == "op-b"
        assert int(lease["spec"]["leaseTransitions"]) >= 1
        # EXPIRY-gated takeover, proven from the Lease's own timestamps:
        # op-b acquired only after the dead leader's lease ran out.
        assert _parse_rfc3339(lease["spec"]["acquireTime"]) >= expiry

        # The new leader reconciles fresh CRs.
        client.create(GV, "arksapplications", "default", _cr(
            "ArksApplication", "app2", {
                "replicas": 1, "size": 1, "runtime": "jax",
                "model": {"name": "m1"}, "servedModelName": "served2",
                "modelConfig": "tiny"}))
        wait_for(lambda: "arks-app2-0" in [
            s["metadata"]["name"]
            for s in client.list("apps/v1", "statefulsets")])
    finally:
        b.stop()
        a.stop()
        srv.stop()
