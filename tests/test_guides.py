"""Guided decoding: regex/JSON grammars -> token-table DFAs -> engine.

Parity target: vLLM/SGLang guided decoding (JSON mode, guided_regex)
reachable through the reference's runtime launch path
(arksapplication_controller.go:941-1014)."""

import json
import queue
import threading
import time

import numpy as np
import pytest

from arks_tpu.engine import EngineConfig, InferenceEngine
from arks_tpu.engine.guides import (GuideCompiler, GuideError,
                                    compile_regex_dfa, json_mode_regex)
from arks_tpu.engine.tokenizer import ByteTokenizer
from arks_tpu.engine.types import Request, SamplingParams
from arks_tpu.models import get_config


def _match(table, acc, s: str) -> bool:
    st = 0
    for b in s.encode():
        st = table[st, b]
        if st < 0:
            return False
    return bool(acc[st])


# ---------------------------------------------------------------------------
# Character DFA
# ---------------------------------------------------------------------------

def test_regex_dfa_basics():
    t, a = compile_regex_dfa(r"[a-c]+x?")
    assert _match(t, a, "abc") and _match(t, a, "abcx")
    assert not _match(t, a, "") and not _match(t, a, "x")
    assert not _match(t, a, "abxy")

    t, a = compile_regex_dfa(r"(foo|ba*r)\d{2,3}")
    assert _match(t, a, "foo12") and _match(t, a, "br123")
    assert _match(t, a, "baaar99")
    assert not _match(t, a, "foo1") and not _match(t, a, "foo1234")

    # Escapes, classes, negation, dot-excludes-newline.
    t, a = compile_regex_dfa(r"[^x]\.")
    assert _match(t, a, "y.") and not _match(t, a, "x.")
    t, a = compile_regex_dfa(r".")
    assert _match(t, a, "q") and not _match(t, a, "\n")


def test_regex_dfa_rejects_bad_patterns():
    # Includes non-ASCII class bounds and escapes: they must raise
    # GuideError (HTTP 400), never OverflowError (HTTP 500).
    for bad in ["(", "a{2,1}", "[z-a]", "*a", "a{x}", "[a-Ā]",
                "\\é"]:
        with pytest.raises(GuideError):
            compile_regex_dfa(bad)


def test_json_mode_grammar():
    t, a = compile_regex_dfa(json_mode_regex(3))
    good = ['{}', '{"a": 1}', '{"a": [1, 2.5e3, "x"], "b": {"c": null}}',
            '{"k": {"l": {"m": true}}}', ' { "a" : -0.5 } ',
            '{"s": "esc \\" \\\\ \\u00ff ok"}']
    bad = ['', '[]', '{"a": }', '{a: 1}', '{"a": 1,}', '{"a": 01}',
           '{"a": "\n"}', '{"k": {"l": {"m": {"n": 1}}}}']  # depth 4 > 3
    for s in good:
        assert _match(t, a, s), s
    for s in bad:
        assert not _match(t, a, s), s


def test_json_schema_regex():
    from arks_tpu.engine.guides import json_schema_regex
    schema = {
        "type": "object",
        "properties": {
            "name": {"type": "string", "maxLength": 10},
            "age": {"type": "integer"},
            "tags": {"type": "array", "items": {"type": "string"},
                     "minItems": 1, "maxItems": 2},
            "mood": {"enum": ["happy", "sad", 3]},
            "nick": {"type": "string"},
        },
        "required": ["name", "age", "tags", "mood"],
    }
    t, a = compile_regex_dfa(json_schema_regex(schema))
    good = [
        '{"name": "bo", "age": 3, "tags": ["x"], "mood": "sad"}',
        '{"name": "", "age": 0, "tags": ["a", "b"], "mood": 3, '
        '"nick": "z"}',
    ]
    bad = [
        '{"age": 3, "name": "bo", "tags": ["x"], "mood": "sad"}',  # order
        '{"name": "bo", "age": 3.5, "tags": ["x"], "mood": "sad"}',
        '{"name": "bo", "age": 3, "tags": [], "mood": "sad"}',     # minItems
        '{"name": "bo", "age": 3, "tags": ["a","b","c"], "mood": "sad"}',
        '{"name": "bo", "age": 3, "tags": ["x"], "mood": "angry"}',
        '{"name": "longerthanten!", "age": 3, "tags": ["x"], "mood": 3}',
        '{"name": "bo", "age": 3, "tags": ["x"]}',                 # missing
    ]
    for s in good:
        assert _match(t, a, s), s
    for s in bad:
        assert not _match(t, a, s), s

    # anyOf, const, $refs with bounded recursion.
    t, a = compile_regex_dfa(json_schema_regex({
        "anyOf": [{"const": "yes"}, {"type": "object", "properties": {
            "next": {"$ref": "#/$defs/node"}}, "required": ["next"]}],
        "$defs": {"node": {"type": "null"}}}))
    assert _match(t, a, '"yes"') and _match(t, a, '{"next": null}')
    assert not _match(t, a, "no")

    with pytest.raises(GuideError):
        json_schema_regex({"type": "object", "properties": {
            "opt": {"type": "integer"}}, "required": []})
    # required names absent from properties must raise, not silently drop.
    with pytest.raises(GuideError, match="not declared"):
        json_schema_regex({"type": "object", "properties": {
            "a": {"type": "integer"}}, "required": ["a", "b"]})
    # minLength alone leaves the tail unbounded (no invented max).
    t, a = compile_regex_dfa(json_schema_regex(
        {"type": "string", "minLength": 2}))
    assert _match(t, a, '"' + "x" * 5000 + '"')
    assert not _match(t, a, '"x"')
    # Property names are JSON-escaped, not just regex-escaped.
    t, a = compile_regex_dfa(json_schema_regex({
        "type": "object", "properties": {'a"b': {"type": "null"}}}))
    assert _match(t, a, '{"a\\"b": null}')
    assert not _match(t, a, '{"a"b": null}')


# ---------------------------------------------------------------------------
# Token tables / compiler registry
# ---------------------------------------------------------------------------

def test_guide_compiler_walk_and_budget():
    tok = ByteTokenizer()
    gc = GuideCompiler(tok, tok.vocab_size, eos_ids=(0,))
    g = gc.compile("json")
    assert gc.compile("json") is g  # cached
    row = g.start_row
    for tid in tok.encode('{"a": [1, true]}'):
        assert gc.allowed(row)[tid]
        row = gc.next_row(row, tid)
    assert gc.allowed(row)[0], "eos allowed once the object closes"
    term = gc.next_row(row, 0)
    assert gc.allowed(term).all(), "terminal row must not degenerate logits"
    # eos is NOT allowed mid-object.
    row = g.start_row
    for tid in tok.encode('{"a"'):
        row = gc.next_row(row, tid)
    assert not gc.allowed(row)[0]
    # Specials without byte representations never advance a guide.
    assert not gc.allowed(g.start_row)[1]  # bos

    tiny = GuideCompiler(tok, tok.vocab_size, eos_ids=(0,), max_rows=4)
    with pytest.raises(GuideError, match="row budget"):
        tiny.compile("json")


def test_multiple_guides_independent_rows():
    tok = ByteTokenizer()
    gc = GuideCompiler(tok, tok.vocab_size, eos_ids=(0,))
    g1 = gc.compile("regex", "(yes|no)")
    g2 = gc.compile("regex", "[0-9]+")
    assert g1.guide_id != g2.guide_id
    assert (g1.start_row + g1.n_states) <= g2.start_row
    row = g2.start_row
    digits = tok.encode("42")
    for tid in digits:
        assert gc.allowed(row)[tid]
        row = gc.next_row(row, tid)
    assert gc.allowed(row)[0]          # accept: eos ok
    assert gc.allowed(row)[digits[0]]  # [0-9]+ continues


# ---------------------------------------------------------------------------
# Engine end-to-end
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def engine():
    cfg = get_config("tiny")
    ecfg = EngineConfig(model="tiny", num_slots=2, max_cache_len=96,
                        prefill_buckets=(8, 16, 32), steps_per_dispatch=4)
    eng = InferenceEngine(cfg, ecfg, ByteTokenizer())
    eng.start()
    yield eng
    eng.stop()


def _run(eng, prompt: str, guide, temperature=0.0, seed=None,
         max_tokens=48):
    req = Request(
        request_id=f"g-{guide}-{temperature}-{seed}",
        prompt_ids=ByteTokenizer().encode(prompt),
        params=SamplingParams(max_tokens=max_tokens,
                              temperature=temperature, seed=seed,
                              guide=guide))
    eng.add_request(req)
    toks, fin = [], None
    while True:
        out = req.outputs.get(timeout=60)
        toks.extend(out.token_ids)
        if out.finished:
            fin = out
            break
    return ByteTokenizer().decode(toks), fin, toks


def test_engine_regex_guide_greedy_and_sampled(engine):
    """A closed-form regex forces the full round trip: the DFA reaches its
    accept state, only eos remains legal, and the output matches the
    pattern exactly — greedy AND sampled paths."""
    pat = r'\{"k": (true|false)\}'
    text, fin, _ = _run(engine, "zz", ("regex", pat))
    assert fin.finish_reason == "stop"
    obj = json.loads(text)
    assert obj["k"] in (True, False)
    text2, fin2, _ = _run(engine, "zz", ("regex", pat), temperature=1.0,
                          seed=7)
    assert fin2.finish_reason == "stop"
    assert json.loads(text2)["k"] in (True, False)


def test_engine_json_mode_prefix_valid(engine):
    """JSON mode: every generated prefix stays inside the JSON DFA (no
    dead transition was ever sampled), greedy and sampled."""
    table, acc = compile_regex_dfa(json_mode_regex(3))
    for temp, seed in ((0.0, None), (1.0, 3)):
        text, fin, toks = _run(engine, "qq", ("json", ""), temperature=temp,
                               seed=seed, max_tokens=24)
        st = 0
        for b in text.encode():
            st = table[st, b]
            assert st >= 0, f"dead transition in {text!r}"
        if fin.finish_reason == "stop":
            assert acc[st], f"stopped outside an accept state: {text!r}"


def test_engine_total_guide_matches_unconstrained(engine):
    """A total DFA (over byte tokens) must not change greedy decoding —
    masking is identity when nothing is masked."""
    lo, hi = ByteTokenizer.OFFSET, ByteTokenizer.OFFSET + 256
    for prompt in ("parity", "zq", "ab", "hello", "x7", "mn"):
        _, _, toks_b = _run(engine, prompt, None, max_tokens=8)
        if all(lo <= t < hi for t in toks_b):
            break
    else:
        pytest.skip("tiny model's greedy outputs always leave the byte "
                    "range (vocab rows past the tokenizer are disallowed "
                    "under any guide by design)")
    _, fin_b, toks_b = _run(engine, prompt, None, max_tokens=8)
    guided, fin_g, toks_g = _run(engine, prompt, ("regex", r"(.|\n)*"),
                                 max_tokens=8)
    assert toks_g == toks_b
    assert fin_g.finish_reason == fin_b.finish_reason


def test_engine_bad_pattern_rejected_on_caller_thread(engine):
    req = Request(request_id="bad", prompt_ids=[5, 6],
                  params=SamplingParams(max_tokens=4,
                                        guide=("regex", "(unclosed")))
    with pytest.raises(GuideError):
        engine.add_request(req)


@pytest.fixture(scope="module")
def hf_tokenizer(tmp_path_factory):
    """A real byte-level-BPE HF tokenizer built locally (no hub access):
    the production tokenizer shape (Qwen2/Llama-3/GPT-2 style), with
    multi-byte merged tokens like '{\"' and 'Ġtrue'."""
    from tokenizers import Tokenizer, decoders, models, pre_tokenizers, trainers

    tok = Tokenizer(models.BPE())
    tok.pre_tokenizer = pre_tokenizers.ByteLevel(add_prefix_space=False)
    tok.decoder = decoders.ByteLevel()
    trainer = trainers.BpeTrainer(
        vocab_size=400, special_tokens=["<|end|>"],
        initial_alphabet=pre_tokenizers.ByteLevel.alphabet())
    tok.train_from_iterator(
        ['{"name": "value", "ok": true, "n": 123}',
         'hello world json {"a": [1, 2], "b": false}'] * 50, trainer)
    d = tmp_path_factory.mktemp("hftok")
    tok.save(str(d / "tokenizer.json"))
    (d / "config.json").write_text('{"model_type": "gpt2"}')
    from arks_tpu.engine.tokenizer import HFTokenizer

    hf = HFTokenizer(str(d))
    hf._tok.eos_token = "<|end|>"
    return hf


def test_token_byte_table_hf(hf_tokenizer):
    """The byte table inverts the GPT-2 byte<->unicode mapping: joining a
    real encoding's token bytes reproduces the input bytes exactly."""
    from arks_tpu.engine.guides import token_byte_table

    hf = hf_tokenizer
    vocab = len(hf._tok)
    arr, lens = token_byte_table(hf, vocab)
    for s in ['{"ok": true}', 'hello world', '{"n": 123, "b": false}']:
        ids = hf.encode(s)
        got = b"".join(bytes(arr[i, : lens[i]]) for i in ids)
        assert got == s.encode(), s
    # The special token has no byte representation.
    assert lens[hf._tok.eos_token_id if hf._tok.eos_token_id is not None
                else 0] == 0


def test_guide_walk_hf_tokenizer(hf_tokenizer):
    """Guided decoding against merged multi-byte BPE tokens: a real
    encoding of a matching document walks the token DFA to accept, and
    eos flips legal exactly there."""
    hf = hf_tokenizer
    gc = GuideCompiler(hf, len(hf._tok), eos_ids=(0,))
    gc.compile("json")
    g = gc.compile("regex", r'\{"ok": (true|false)\}')
    row = g.start_row
    for tid in hf.encode('{"ok": true}'):
        assert gc.allowed(row)[tid], (row, tid)
        row = gc.next_row(row, tid)
    assert gc.allowed(row)[0]
    # Mid-document eos is illegal.
    row = g.start_row
    for tid in hf.encode('{"ok"'):
        row = gc.next_row(row, tid)
    assert not gc.allowed(row)[0]
    # JSON mode accepts the same doc through merged tokens.
    gj = gc.lookup("json")
    row = gj.start_row
    for tid in hf.encode('{"n": 1, "b": [true, null]}'):
        assert gc.allowed(row)[tid]
        row = gc.next_row(row, tid)
    assert gc.allowed(row)[0]


def test_engine_guided_with_hf_tokenizer(hf_tokenizer):
    """Full engine round trip on the HF tokenizer: the guide must drive
    multi-byte BPE pieces to a valid document."""
    cfg = get_config("tiny")
    ecfg = EngineConfig(model="tiny", num_slots=2, max_cache_len=64,
                        prefill_buckets=(8, 16), steps_per_dispatch=2)
    eng = InferenceEngine(cfg, ecfg, hf_tokenizer)
    eng.start()
    try:
        req = Request(request_id="hf1",
                      prompt_ids=hf_tokenizer.encode("hello"),
                      params=SamplingParams(
                          max_tokens=24, temperature=0.0,
                          guide=("regex", r'\{"ok": (true|false)\}')))
        eng.add_request(req)
        toks = []
        while True:
            out = req.outputs.get(timeout=120)
            toks.extend(out.token_ids)
            if out.finished:
                break
        assert out.finish_reason == "stop"
        assert json.loads(hf_tokenizer.decode(toks))["ok"] in (True, False)
    finally:
        eng.stop()


# ---------------------------------------------------------------------------
# Non-blocking compile pipeline + LRU eviction
# ---------------------------------------------------------------------------

def test_concurrent_compiles_of_same_key_build_once():
    """N threads compiling one (kind, pattern) dedupe onto a single
    expensive build through the in-flight ticket."""
    tok = ByteTokenizer()
    gc = GuideCompiler(tok, tok.vocab_size, eos_ids=(0,))
    builds: list[str] = []
    orig = gc._build

    def counting_build(rx):
        builds.append(rx)
        time.sleep(0.2)  # widen the race window
        return orig(rx)

    gc._build = counting_build
    out: list = []
    threads = [threading.Thread(
        target=lambda: out.append(gc.compile("regex", "[0-9]+")))
        for _ in range(6)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert len(builds) == 1, "same-key compiles must dedupe onto one build"
    assert len(out) == 6 and all(g is out[0] for g in out)


def test_lru_eviction_pins_and_row_reuse():
    tok = ByteTokenizer()
    gc = GuideCompiler(tok, tok.vocab_size, eos_ids=(0,), max_guides=2)
    g1 = gc.compile("regex", "a+")
    g2 = gc.compile("regex", "b+")
    v0 = gc.version
    gc.acquire("regex", "b+")  # pin g2 (simulates an active slot)
    g3 = gc.compile("regex", "c+")  # budget full -> evicts g1 (LRU, unpinned)
    assert gc.lookup("regex", "a+") is None
    assert gc.lookup("regex", "b+") is g2, "pinned guide must survive"
    assert gc.version > v0, "eviction + publish must bump version"
    assert g3.guide_id == g1.guide_id, "evicted id is reused"
    assert g3.start_row == g1.start_row, "evicted row span is reused"
    # The interval index resolves rows correctly after the repack.
    row = g3.start_row
    for tid in tok.encode("cc"):
        assert gc.allowed(row)[tid]
        row = gc.next_row(row, tid)
    assert gc.allowed(row)[0]
    # Every guide pinned -> a new pattern fails with a clean GuideError...
    gc.acquire("regex", "c+")
    with pytest.raises(GuideError, match="budget"):
        gc.compile("regex", "d+")
    # ...and releasing a pin makes the same pattern compile (evicting it).
    gc.release("regex", "b+")
    g4 = gc.compile("regex", "d+")
    assert gc.lookup("regex", "b+") is None
    assert gc.lookup("regex", "d+") is g4


def test_engine_slow_compile_does_not_block_unguided_stream():
    """A cold guide compile (artificially slowed to 2.5 s) must not stall
    the scheduler: a concurrent unguided stream decodes to completion
    while the compile runs, and the guided request then completes with
    grammar-valid output."""
    cfg = get_config("tiny")
    ecfg = EngineConfig(model="tiny", num_slots=2, max_cache_len=96,
                        prefill_buckets=(8, 16, 32), steps_per_dispatch=4)
    eng = InferenceEngine(cfg, ecfg, ByteTokenizer())
    eng.start()
    try:
        _run(eng, "warm", None, max_tokens=4)  # jit warmup off the clock
        orig = eng.guides._build

        def slow_build(rx):
            time.sleep(2.5)
            return orig(rx)

        eng.guides._build = slow_build
        pat = r'\{"k": (true|false)\}'
        greq = Request(request_id="slowg",
                       prompt_ids=ByteTokenizer().encode("zz"),
                       params=SamplingParams(max_tokens=48, temperature=0.0,
                                             guide=("regex", pat)))
        eng.add_request(greq)
        time.sleep(0.1)  # compile is now in flight on the worker pool
        t0 = time.monotonic()
        _, fin_u, _ = _run(eng, "ab", None, max_tokens=8)
        unguided_s = time.monotonic() - t0
        assert unguided_s < 2.0, (
            f"unguided stream took {unguided_s:.2f}s — it stalled behind "
            "the guide compile")
        toks: list[int] = []
        while True:
            out = greq.outputs.get(timeout=60)
            toks.extend(out.token_ids)
            if out.finished:
                break
        assert out.finish_reason == "stop"
        assert json.loads(ByteTokenizer().decode(toks))["k"] in (True, False)
    finally:
        eng.stop()


def _counter_total(counter) -> float:
    return sum(counter._values.values())


def test_engine_lru_eviction_end_to_end(monkeypatch):
    """ARKS_GUIDE_MAX + 4 distinct schemas served sequentially on one
    engine: LRU eviction keeps admitting (no restart, no 400), evictions
    advance the metric, and guided outputs stay grammar-valid after
    eviction-driven device-table refreshes."""
    monkeypatch.setenv("ARKS_GUIDE_MAX", "3")
    cfg = get_config("tiny")
    ecfg = EngineConfig(model="tiny", num_slots=2, max_cache_len=96,
                        prefill_buckets=(8, 16, 32), steps_per_dispatch=4)
    eng = InferenceEngine(cfg, ecfg, ByteTokenizer())
    assert eng.guides.max_guides == 3
    eng.start()
    try:
        for i in range(3 + 4):
            pat = r'\{"k%d": (true|false)\}' % i
            text, fin, _ = _run(eng, "zz", ("regex", pat), max_tokens=48)
            assert fin.finish_reason == "stop", (i, fin)
            assert json.loads(text)[f"k{i}"] in (True, False)
        assert _counter_total(
            eng.metrics.guide_cache_evictions_total) >= 4
        assert eng.metrics.guide_registry_guides_in_use.get() <= 3
    finally:
        eng.stop()


def test_engine_all_guides_pinned_rejects_cleanly(monkeypatch):
    """With ARKS_GUIDE_MAX=1 and the only guide pinned by a running slot,
    a second pattern gets a per-request error (HTTP 400 at the server),
    not a dropped stream — and once the pin releases, the same pattern
    compiles via eviction."""
    monkeypatch.setenv("ARKS_GUIDE_MAX", "1")
    cfg = get_config("tiny")
    ecfg = EngineConfig(model="tiny", num_slots=2, max_cache_len=256,
                        prefill_buckets=(8, 16), steps_per_dispatch=4)
    tok = ByteTokenizer()
    eng = InferenceEngine(cfg, ecfg, tok)
    eng.start()
    try:
        # Long-running guided request: pins the single guide slot.
        r1 = Request(request_id="pin1", prompt_ids=tok.encode("zz"),
                     params=SamplingParams(max_tokens=180, temperature=0.0,
                                           guide=("regex", "(a|b)+")))
        eng.add_request(r1)
        out1 = r1.outputs.get(timeout=60)  # first token -> slot registered
        assert not out1.finished
        # Second pattern: compiles fine, but publish finds the budget full
        # with every guide pinned -> per-request error output.
        r2 = Request(request_id="pin2", prompt_ids=tok.encode("q"),
                     params=SamplingParams(max_tokens=8, temperature=0.0,
                                           guide=("regex", "[0-9]+")))
        eng.add_request(r2)
        while True:
            out2 = r2.outputs.get(timeout=60)
            if out2.finished:
                break
        assert out2.finish_reason == "error"
        assert "guide" in (out2.error or "")
        # Drain the pinning request; its _finish releases the pin.
        toks1 = list(out1.token_ids)
        while True:
            o = r1.outputs.get(timeout=120)
            toks1.extend(o.token_ids)
            if o.finished:
                break
        assert set(tok.decode(toks1)) <= {"a", "b"}
        # Now the same second pattern succeeds (evicts the released guide).
        text3, fin3, _ = _run(eng, "q", ("regex", "[0-9]{2}"), max_tokens=24)
        assert fin3.finish_reason == "stop"
        assert text3.isdigit() and len(text3) == 2
    finally:
        eng.stop()


@pytest.mark.slow
def test_guided_cold_vs_warm_admit_bench():
    """Micro-benchmark (BENCH rounds track bench.py's guided_cold_start_s;
    this is the CPU-tier counterpart): admit-to-first-token with a cold vs
    warm guide, plus the headline assertion that scheduler progress during
    a background compile stays bounded on CPU."""
    cfg = get_config("tiny")
    ecfg = EngineConfig(model="tiny", num_slots=2, max_cache_len=96,
                        prefill_buckets=(8, 16, 32), steps_per_dispatch=4)
    eng = InferenceEngine(cfg, ecfg, ByteTokenizer())
    eng.start()
    try:
        _run(eng, "warm", None, max_tokens=4)  # jit warmup

        def ttft(pat: str) -> float:
            req = Request(request_id=f"b-{pat}",
                          prompt_ids=ByteTokenizer().encode("zz"),
                          params=SamplingParams(max_tokens=8,
                                                temperature=0.0,
                                                guide=("regex", pat)))
            t0 = time.monotonic()
            eng.add_request(req)
            first = req.outputs.get(timeout=120)
            dt = time.monotonic() - t0
            while not first.finished:
                first = req.outputs.get(timeout=120)
            return dt

        cold = ttft(r'\{"bench": [0-9]\}')
        warm = ttft(r'\{"bench": [0-9]\}')
        assert cold > 0 and warm > 0
        # Scheduler responsiveness during a background compile: an
        # unguided request admitted mid-compile must reach its first
        # token well before the compile finishes (loose CPU bound).
        orig = eng.guides._build

        def slow_build(rx):
            time.sleep(2.0)
            return orig(rx)

        eng.guides._build = slow_build
        greq = Request(request_id="b-bg",
                       prompt_ids=ByteTokenizer().encode("zz"),
                       params=SamplingParams(max_tokens=8, temperature=0.0,
                                             guide=("regex", "[a-f]+")))
        eng.add_request(greq)
        time.sleep(0.05)
        ureq = Request(request_id="b-un",
                       prompt_ids=ByteTokenizer().encode("ab"),
                       params=SamplingParams(max_tokens=4, temperature=0.0))
        t0 = time.monotonic()
        eng.add_request(ureq)
        out = ureq.outputs.get(timeout=60)
        step_bound = time.monotonic() - t0
        while not out.finished:
            out = ureq.outputs.get(timeout=60)
        while True:
            o = greq.outputs.get(timeout=60)
            if o.finished:
                break
        assert step_bound < 1.5, (
            f"admit-to-first-token {step_bound:.2f}s during a background "
            "compile — the scheduler blocked on compilation")
        print(f"guided admit-to-first-token: cold={cold:.3f}s "
              f"warm={warm:.3f}s mid-compile-unguided={step_bound:.3f}s")
    finally:
        eng.stop()


def test_engine_guide_with_chunked_prefill():
    """Guided first-token sampling on the chunked-prefill path: the prompt
    exceeds the one-shot buckets, so the first token comes from
    _sample_one with the guide columns, and the DFA row is host-advanced
    into the slot registration."""
    cfg = get_config("tiny")
    ecfg = EngineConfig(model="tiny", num_slots=2, max_cache_len=64,
                        prefill_buckets=(8,), prefill_chunk=8,
                        steps_per_dispatch=2)
    eng = InferenceEngine(cfg, ecfg, ByteTokenizer())
    eng.start()
    try:
        pat = r'\{"n": [0-9]\}'
        text, fin, _ = _run(eng, "x" * 20, ("regex", pat), max_tokens=24)
        assert fin.finish_reason == "stop"
        assert json.loads(text)["n"] in range(10)
    finally:
        eng.stop()
