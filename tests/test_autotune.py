"""Persisted kernel-autotune table: mode gating, cache-path precedence,
persist -> load -> reuse round-trips (including through mixed_grid_plan,
the consumer the kernels actually resolve statics through), sweep
winner selection, and the engine's sweep-at-warm-up path."""

import json
import os

import pytest

from arks_tpu.ops import autotune


@pytest.fixture(autouse=True)
def _isolated_table(monkeypatch, tmp_path):
    """Every test gets its own table file and a cold in-memory cache."""
    monkeypatch.setenv("ARKS_KERNEL_TUNE_CACHE",
                       str(tmp_path / "kernel_tune.json"))
    monkeypatch.setenv("ARKS_KERNEL_TUNE", "cached")
    autotune.invalidate_cache()
    yield
    autotune.invalidate_cache()


def test_mode_validation(monkeypatch):
    for m in ("off", "cached", "sweep"):
        monkeypatch.setenv("ARKS_KERNEL_TUNE", m)
        assert autotune.mode() == m
    monkeypatch.setenv("ARKS_KERNEL_TUNE", "always")
    with pytest.raises(ValueError, match="ARKS_KERNEL_TUNE"):
        autotune.mode()


def test_cache_path_precedence(monkeypatch, tmp_path):
    monkeypatch.setenv("ARKS_KERNEL_TUNE_CACHE", str(tmp_path / "x.json"))
    monkeypatch.setenv("ARKS_MODEL_DIR", str(tmp_path / "model"))
    assert autotune.cache_path() == str(tmp_path / "x.json")
    monkeypatch.delenv("ARKS_KERNEL_TUNE_CACHE")
    assert autotune.cache_path() == str(tmp_path / "model" /
                                        "kernel_tune.json")
    monkeypatch.delenv("ARKS_MODEL_DIR")
    assert autotune.cache_path().endswith(
        os.path.join(".cache", "arks_tpu", "kernel_tune.json"))


def test_record_persists_and_lookup_round_trips():
    sig = autotune.mixed_signature(hkv=2, g=3, d=32, page=128, qmax=16,
                                   kv="int8")
    assert autotune.lookup("paged_mixed", sig) is None
    autotune.record("paged_mixed", sig, {"block_q": 8, "dma_depth": 4})
    # Through the write-through in-memory table...
    assert autotune.lookup("paged_mixed", sig) == {"block_q": 8,
                                                   "dma_depth": 4}
    # ...and through a cold LOAD from the JSON on disk.
    autotune.invalidate_cache()
    assert autotune.lookup("paged_mixed", sig) == {"block_q": 8,
                                                   "dma_depth": 4}
    on_disk = json.loads(open(autotune.cache_path()).read())
    assert on_disk["paged_mixed"][sig] == {"block_q": 8, "dma_depth": 4}


def test_mode_off_ignores_table(monkeypatch):
    sig = autotune.decode_signature(b=4, hkv=2, g=3, d=32, page=128,
                                    kv="int8")
    autotune.record("paged_decode", sig, {"block_b": 32})
    monkeypatch.setenv("ARKS_KERNEL_TUNE", "off")
    assert autotune.lookup("paged_decode", sig) is None
    monkeypatch.setenv("ARKS_KERNEL_TUNE", "cached")
    assert autotune.lookup("paged_decode", sig) == {"block_b": 32}


def test_signatures_embed_topology_and_shape():
    a = autotune.mixed_signature(hkv=2, g=3, d=32, page=128, qmax=16,
                                 kv="int8")
    b = autotune.mixed_signature(hkv=2, g=3, d=32, page=128, qmax=16,
                                 kv="int4")
    assert a != b and autotune.topology() in a


def test_mixed_grid_plan_honors_cached_entry():
    """The consumer path: mixed_grid_plan resolves block_q/dma_depth from
    the table, falls back to the heuristic on a miss, and explicit
    arguments always win over the table."""
    from arks_tpu.ops.paged_attention import mixed_grid_plan

    kw = dict(hkv=2, g=3, d=32, page=128, kv="float32")
    plan = mixed_grid_plan(48, **kw)
    assert plan["block_q"] == 32 and plan["dma_depth"] == 2  # heuristics
    sig = autotune.mixed_signature(qmax=48, **kw)
    autotune.record("paged_mixed", sig, {"block_q": 16, "dma_depth": 4})
    autotune.invalidate_cache()
    plan = mixed_grid_plan(48, **kw)
    assert plan["block_q"] == 16 and plan["dma_depth"] == 4
    assert plan["qpad"] == 48 and plan["num_qb"] == 3
    # Explicit overrides beat the table.
    assert mixed_grid_plan(48, block_q=8, **kw)["block_q"] == 8
    # A different qmax is a different signature: heuristic again.
    assert mixed_grid_plan(40, **kw)["block_q"] == 32


def test_sweep_picks_and_persists_fastest(monkeypatch):
    import time

    sig = autotune.mixed_signature(hkv=1, g=1, d=8, page=8, qmax=4,
                                   kv="float32")
    calls = []

    def bench(block_q):
        calls.append(block_q)
        time.sleep(0.02 if block_q == 4 else 0.001)

    best = autotune.sweep("paged_mixed", sig,
                          [{"block_q": 4}, {"block_q": 2}], bench,
                          repeats=2)
    assert best == {"block_q": 2}
    assert calls.count(4) == calls.count(2) == 3  # warm-up + 2 timed
    autotune.invalidate_cache()
    assert autotune.lookup("paged_mixed", sig) == {"block_q": 2}


def test_sweep_skips_infeasible_candidates():
    def bench(block_q):
        if block_q == 8:
            raise ValueError("infeasible")

    best = autotune.sweep("k", "s", [{"block_q": 8}, {"block_q": 2}], bench)
    assert best == {"block_q": 2}
    with pytest.raises(RuntimeError, match="every candidate"):
        autotune.sweep("k", "s2", [{"block_q": 8}], bench)


def test_ensure_is_mode_aware(monkeypatch):
    sig = "s"
    swept = []

    def bench(block_q):
        swept.append(block_q)

    # cached + miss: no sweep, heuristics (None).
    assert autotune.ensure("k", sig, [{"block_q": 2}], bench) is None
    assert not swept
    # sweep + miss: sweeps once, then the cached entry short-circuits.
    monkeypatch.setenv("ARKS_KERNEL_TUNE", "sweep")
    assert autotune.ensure("k", sig, [{"block_q": 2}], bench) == {
        "block_q": 2}
    n = len(swept)
    assert autotune.ensure("k", sig, [{"block_q": 2}], bench) == {
        "block_q": 2}
    assert len(swept) == n


def test_engine_sweep_mode_tunes_mixed_kernel(monkeypatch):
    """ARKS_KERNEL_TUNE=sweep at engine construction: _warm_autotune
    benches the mixed kernel on the engine's own pool BEFORE the first
    dispatch and persists a winner under the engine's mixed signature —
    and the served stream matches the untuned engine's byte-for-byte
    (block sizes change the schedule, never the math)."""
    from arks_tpu.engine import (EngineConfig, InferenceEngine, Request,
                                 SamplingParams)
    from arks_tpu.engine.tokenizer import ByteTokenizer
    from arks_tpu.models import get_config
    from arks_tpu.models import transformer as tf

    monkeypatch.setenv("ARKS_MIXED_STEP", "1")
    monkeypatch.setenv("ARKS_ATTN_IMPL", "pallas")
    monkeypatch.setenv("ARKS_MIXED_GRID", "ragged")
    cfg = get_config("tiny")

    def run(tune_mode):
        monkeypatch.setenv("ARKS_KERNEL_TUNE", tune_mode)
        autotune.invalidate_cache()
        eng = InferenceEngine(cfg, EngineConfig(
            model="tiny", num_slots=2, max_cache_len=64,
            prefill_buckets=(8, 16, 32), steps_per_dispatch=4,
            prefill_chunk=16, kv_layout="paged", prefix_cache_mb=0),
            ByteTokenizer())
        req = Request("t0", [5, 6, 7], SamplingParams(
            max_tokens=4, temperature=0.0, ignore_eos=True))
        eng.add_request(req)
        for _ in range(400):
            eng.step(block_s=0.01)
            if (eng.num_running == 0 and eng._queue.empty()
                    and not eng._prefilling):
                break
        ids = []
        while True:
            out = req.outputs.get(timeout=120)
            ids.extend(out.token_ids)
            if out.finished:
                return eng, ids

    eng, swept_ids = run("sweep")
    sig = autotune.mixed_signature(
        hkv=cfg.num_kv_heads, g=cfg.num_heads // cfg.num_kv_heads,
        d=tf.cache_head_dim(cfg, eng._pad_head()), page=eng._page_size(),
        qmax=eng._mixed_budget + 1,
        kv=str(eng._cache.k.dtype))
    autotune.invalidate_cache()
    entry = autotune.lookup("paged_mixed", sig)
    assert entry and "block_q" in entry and "dma_depth" in entry
    assert eng.resolved_config["kernel_tune"] == "sweep"

    _, off_ids = run("off")
    assert swept_ids == off_ids
