"""Deployment assets stay loadable and the standalone entrypoints work."""

import glob
import json
import os
import signal
import subprocess
import sys
import time
import urllib.request

import yaml

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_deploy_yaml_parses():
    paths = glob.glob(os.path.join(ROOT, "deploy", "**", "*.yaml"), recursive=True)
    assert len(paths) >= 4
    for p in paths:
        docs = [d for d in yaml.safe_load_all(open(p)) if d]
        assert docs, p
        for d in docs:
            assert "kind" in d and "metadata" in d, p


def test_crds_cover_six_kinds_with_status_subresource():
    """CRD schema parity (reference config/crd/bases/): all six arks.ai
    kinds, structural schemas, status subresource enabled (the live
    operator projects status through it)."""
    docs = [d for d in yaml.safe_load_all(
        open(os.path.join(ROOT, "deploy", "crds.yaml"))) if d]
    kinds = {d["spec"]["names"]["kind"]: d for d in docs}
    assert set(kinds) == {
        "ArksApplication", "ArksDisaggregatedApplication", "ArksModel",
        "ArksEndpoint", "ArksToken", "ArksQuota"}
    from arks_tpu.control.live import KINDS
    plurals = {plural for _, plural, _ in KINDS}
    for kind, d in kinds.items():
        assert d["spec"]["group"] == "arks.ai"
        assert d["spec"]["names"]["plural"] in plurals
        v = d["spec"]["versions"][0]
        assert v["name"] == "v1" and v["served"] and v["storage"]
        assert v["subresources"].get("status") == {}, kind
        if kind == "ArksApplication":
            # Scale subresource: HPA / kubectl scale drive replicas.
            assert set(v["subresources"]) == {"status", "scale"}
            scale = v["subresources"]["scale"]
            assert scale["specReplicasPath"] == ".spec.replicas"
            assert scale["statusReplicasPath"] == ".status.replicas"
        else:
            # No stray subresources on the other kinds (a copy-pasted
            # scale block would carry wrong paths).
            assert set(v["subresources"]) == {"status"}, kind
        assert v["schema"]["openAPIV3Schema"]["type"] == "object"
        # metadata.name = <plural>.<group>
        assert d["metadata"]["name"] == f"{d['spec']['names']['plural']}.arks.ai"


def test_grafana_dashboard_parses():
    d = json.load(open(os.path.join(ROOT, "deploy", "grafana",
                                    "runtime-dashboard.json")))
    assert d["panels"] and all("targets" in p for p in d["panels"])


def test_download_worker_requires_env():
    out = subprocess.run(
        [sys.executable, "-m", "arks_tpu.control.download"],
        capture_output=True, text=True, timeout=60, env={
            **os.environ, "MODEL_NAME": "", "MODEL_PATH": ""})
    assert out.returncode == 2


def test_standalone_gateway_file_provider(tmp_path):
    """python -m arks_tpu.gateway --manifests ... serves /v1/models (the
    reference gateway's file config-provider mode)."""
    manifest = tmp_path / "gw.yaml"
    manifest.write_text("""
kind: Endpoint
metadata: {name: m1, namespace: ns}
spec: {}
---
kind: Token
metadata: {name: t, namespace: ns}
spec:
  token: sk-file
  qos:
    - endpoint: {name: m1}
""")
    port = 18231
    proc = subprocess.Popen(
        [sys.executable, "-m", "arks_tpu.gateway",
         "--manifests", str(manifest), "--host", "127.0.0.1",
         "--port", str(port)],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
    try:
        deadline = time.monotonic() + 30
        body = None
        while time.monotonic() < deadline:
            try:
                req = urllib.request.Request(
                    f"http://127.0.0.1:{port}/v1/models",
                    headers={"Authorization": "Bearer sk-file"})
                body = json.load(urllib.request.urlopen(req, timeout=5))
                break
            except OSError:
                time.sleep(0.2)
        assert body is not None, "gateway never came up"
        assert [m["id"] for m in body["data"]] == ["m1"]
    finally:
        proc.send_signal(signal.SIGTERM)
        proc.wait(timeout=10)


def test_per_kind_samples_parse_and_render():
    """Every per-kind sample (examples/samples/, reference config/samples
    parity) loads through the manifest path and the workload-bearing ones
    render to valid K8s docs."""
    import glob

    from arks_tpu.control.__main__ import apply_manifests
    from arks_tpu.control.k8s_export import render_store
    from arks_tpu.control.store import Store

    store = Store()
    files = sorted(glob.glob("examples/samples/*.yaml"))
    assert len(files) == 6
    for f in files:
        apply_manifests(store, f)
    docs = render_store(store)
    kinds = {d["kind"] for d in docs}
    assert {"PersistentVolumeClaim", "Job", "StatefulSet", "Service",
            "Deployment", "HTTPRoute", "PodGroup"} <= kinds
    # The unified disagg sample yields exactly one unit PodGroup + the
    # standalone app's per-group PodGroups (2 replicas).
    pgs = [d["metadata"]["name"] for d in docs if d["kind"] == "PodGroup"]
    assert sorted(pgs) == ["arks-qwen-pd", "arks-qwen2.5-7b-0",
                           "arks-qwen2.5-7b-1"]


def test_flagship_examples_render():
    """BASELINE.json configs #2, #3 and #5 as checked-in examples: the
    north-star Qwen2.5-7B on one v5e chip, Llama-3-8B TP over v5e-8, and
    Qwen2.5-72B on multi-host v5p-16 with an Orbax-converting Model — all
    must load and render to gangs with the right topology, size, and
    rendezvous env."""
    import glob

    from arks_tpu.control.__main__ import apply_manifests
    from arks_tpu.control.k8s_export import render_store
    from arks_tpu.control.store import Store

    store = Store()
    files = sorted(glob.glob("examples/flagship/*.yaml"))
    assert len(files) == 3
    for f in files:
        apply_manifests(store, f)
    docs = render_store(store)
    sts = {d["metadata"]["name"]: d for d in docs
           if d["kind"] == "StatefulSet"}

    # #2: the north-star perf config — one chip, one host, w-int8.
    v5e1 = sts["arks-qwen25-7b-0"]
    assert v5e1["spec"]["replicas"] == 1
    pod1 = v5e1["spec"]["template"]["spec"]
    c1 = pod1["containers"][0]
    assert c1["resources"]["limits"]["google.com/tpu"] == "1"
    assert "--weight-dtype" in c1["args"]
    assert c1["args"][c1["args"].index("--weight-dtype") + 1] == "int8"

    # #3: v5e-8 = one host, 8 chips, tp=8; real-tokenizer weights arrive
    # via the Model's HF download (a Job in the render).
    v5e = sts["arks-llama3-8b-0"]
    assert v5e["spec"]["replicas"] == 1
    pod = v5e["spec"]["template"]["spec"]
    assert pod["nodeSelector"]["cloud.google.com/gke-tpu-topology"] == "2x4"
    c = pod["containers"][0]
    assert c["resources"]["limits"]["google.com/tpu"] == "8"
    assert "--tensor-parallel-size" in c["args"]
    assert c["args"][c["args"].index("--tensor-parallel-size") + 1] == "8"

    # #5: v5p-16 = 2 hosts x 4 chips; the gang spans both hosts with the
    # jax.distributed env contract.
    v5p = sts["arks-qwen2.5-72b-0"]
    assert v5p["spec"]["replicas"] == 2
    pod = v5p["spec"]["template"]["spec"]
    assert pod["nodeSelector"]["cloud.google.com/gke-tpu-topology"] == "2x2x2"
    env = {e["name"]: e for e in pod["containers"][0]["env"]}
    assert env["ARKS_NUM_PROCESSES"]["value"] == "2"
    assert "ARKS_COORDINATOR_ADDRESS" in env

    # All three Models download from HF and convert to Orbax shards.
    jobs = [d for d in docs if d["kind"] == "Job"]
    assert len(jobs) == 3
    assert any(j["metadata"]["name"] == "arks-worker-qwen25-7b"
               for j in jobs)
    for j in jobs:
        jenv = {e["name"]: e.get("value") for e in
                j["spec"]["template"]["spec"]["containers"][0]["env"]}
        assert jenv.get("ARKS_CONVERT_ORBAX") == "1"
