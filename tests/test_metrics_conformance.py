"""Prometheus exposition conformance: label-value escaping and a
registry-wide metric-name census (naming conventions + no duplicate
families across the engine, gateway, router, and operator registries)."""

import re

import pytest

from arks_tpu.utils import metrics as prom
from arks_tpu.utils.metrics import _fmt_labels


# ---------------------------------------------------------------- escaping

def test_label_value_backslash_escaped():
    assert _fmt_labels({"path": r"C:\tmp"}) == '{path="C:\\\\tmp"}'


def test_label_value_quote_escaped():
    assert _fmt_labels({"q": 'say "hi"'}) == '{q="say \\"hi\\""}'


def test_label_value_newline_escaped():
    assert _fmt_labels({"m": "a\nb"}) == '{m="a\\nb"}'


def test_label_value_backslash_before_quote_order():
    # \" in the raw value must become \\\" (escape the backslash first,
    # then the quote) — not \\" which would terminate the value early.
    assert _fmt_labels({"v": '\\"'}) == '{v="\\\\\\""}'


def test_escaped_render_is_parseable():
    """A scrape line with hostile label values must round-trip under the
    Prometheus text-format grammar (no raw newline, balanced quotes)."""
    reg = prom.Registry()
    c = reg.counter("hostile_values_total", "escaping probe")
    c.inc(user='a"b', path="c\\d", note="e\nf")
    text = reg.render()
    sample_lines = [ln for ln in text.splitlines()
                    if ln.startswith("hostile_values_total{")]
    assert len(sample_lines) == 1
    line = sample_lines[0]
    assert "\n" not in line
    # Every quote inside the label braces is either a delimiter or escaped.
    body = line[line.index("{") + 1:line.rindex("}")]
    # Unescape per exposition-format rules and check the originals survive.
    m = dict(re.findall(r'(\w+)="((?:\\.|[^"\\])*)"', body))
    unesc = {k: v.replace("\\n", "\n").replace('\\"', '"')
                  .replace("\\\\", "\\") for k, v in m.items()}
    assert unesc == {"user": 'a"b', "path": "c\\d", "note": "e\nf"}


def test_histogram_le_labels_still_render():
    reg = prom.Registry()
    h = reg.histogram("probe_seconds", "h", buckets=[0.1, 1.0])
    h.observe(0.05, op='x"y')
    text = reg.render()
    assert 'le="0.1"' in text and 'op="x\\"y"' in text


# ------------------------------------------------------------- duplicates

def test_duplicate_family_rejected():
    reg = prom.Registry()
    reg.counter("dup_total", "first")
    with pytest.raises(ValueError):
        reg.counter("dup_total", "second")
    with pytest.raises(ValueError):
        reg.gauge("dup_total", "different type, same family")


# ----------------------------------------------------------------- census
#
# The name census (snake_case, _total discipline, no duplicate families
# across components) is now the arkslint ``metrics`` rule — a STATIC
# walk of every registration call, so it covers registries the runtime
# construction below might never instantiate.  These wrappers keep the
# test names; the runtime cross-check at the bottom asserts the live
# registries still agree with what the static census saw.


def _metric_errors(*checks):
    from arks_tpu.analysis import SourceTree, repo_root, run_rules
    findings = run_rules(SourceTree.load(repo_root()), ["metrics"])
    return [f.render() for f in findings
            if f.severity == "error" and f.check in checks]


def test_census_snake_case_and_counter_suffix():
    assert not _metric_errors("name-convention"), (
        _metric_errors("name-convention"))


def test_census_no_family_registered_twice_across_components():
    assert not _metric_errors("duplicate-family"), (
        _metric_errors("duplicate-family"))


def test_tenant_label_cardinality_bounded():
    """Per-tenant metric families must not explode under hostile tenant
    churn: a thousand distinct tenants through the TenantLabels bound
    land on at most cap distinct labels plus the shared "other" bucket,
    and nothing is lost — the counter total still sees every event."""
    from arks_tpu import tenancy
    reg = prom.Registry()
    shed = reg.counter("cardinality_probe_total", "bounded-label probe")
    labels = tenancy.TenantLabels(cap=32)
    for i in range(1000):
        shed.inc(tenant=labels.label(f"churn/user{i}"))
    seen = {dict(k)["tenant"] for k in shed._values}
    assert len(seen) <= 32 + 1
    assert tenancy.OTHER_LABEL in seen
    assert shed.get(tenant=tenancy.OTHER_LABEL) == 1000 - 32
    assert shed.total() == 1000


def test_census_matches_live_registries():
    """The static census must actually see the real registries: every
    family the live engine/gateway/router registries expose appears in
    the static registration walk, and the walk saw a census-sized set."""
    from arks_tpu.analysis import SourceTree, repo_root
    from arks_tpu.analysis.rules import metrics as metrics_rule
    from arks_tpu.engine.engine import EngineMetrics
    from arks_tpu.gateway.metrics import GatewayMetrics, RouterMetrics

    static = {name for _path, _scope, _kind, name, _line
              in metrics_rule.registrations(SourceTree.load(repo_root()))
              if name}
    live = set()
    for reg in (EngineMetrics().registry, GatewayMetrics().registry,
                RouterMetrics().registry):
        live |= {fam.name for fam in reg.families()}
    missing = live - static
    assert not missing, f"live families invisible to the census: {missing}"
    assert len(static) > 40  # the census actually saw the real registries
