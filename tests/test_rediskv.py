"""Shared-counter backend over the Redis protocol (gateway.rediskv):
client/server roundtrips, parity with the in-memory oracle, and the HA
property the reference gets from Redis — two gateway replicas sharing one
rate-limit window and one quota ledger (redis_impl.go parity)."""

import json
import threading
import time
import urllib.error
import urllib.request

import pytest

from arks_tpu.control import resources as res
from arks_tpu.control.store import Store
from arks_tpu.gateway.ratelimiter import (
    MemoryCounterBackend, RateLimiter, window_key)
from arks_tpu.gateway.rediskv import (
    RedisCounterBackend, RedisQuotaService, RespClient, RespServer)
from arks_tpu.gateway.quota import QuotaService
from arks_tpu.gateway.server import Gateway


@pytest.fixture()
def resp():
    srv = RespServer()
    srv.start()
    client = RespClient(srv.host, srv.port)
    yield srv, client
    client.close()
    srv.stop()


# ---------------------------------------------------------------------------
# Protocol roundtrips
# ---------------------------------------------------------------------------


def test_resp_roundtrip(resp):
    _, c = resp
    assert c.command("PING") == "PONG"
    assert c.command("GET", "missing") is None
    assert c.command("SET", "k", "5") == "OK"
    assert c.command("GET", "k") == b"5"
    assert c.command("INCRBY", "k", 3) == 8
    assert c.command("TTL", "k") == -1
    assert c.command("EXPIRE", "k", 100) == 1
    assert 0 < c.command("TTL", "k") <= 100
    assert c.command("DEL", "k") == 1
    assert c.command("TTL", "k") == -2


def test_resp_error_mid_pipeline_keeps_stream_aligned(resp):
    """An -ERR reply inside a pipelined batch raises, but every reply is
    consumed first — the next command must read ITS OWN reply, not a stale
    one (the desync would corrupt every later rate-limit read)."""
    from arks_tpu.gateway.rediskv import RespError
    _, c = resp
    c.command("SET", "ok", "1")
    with pytest.raises(RespError):
        c.pipeline(("BOGUSCMD", "x"), ("INCRBY", "ok", 5))
    # Stream still aligned: the INCRBY above was executed (6) and this GET
    # returns its own value.
    assert c.command("GET", "ok") == b"6"


def test_resp_pipeline_and_expiry(resp):
    _, c = resp
    vals = c.pipeline(("INCRBY", "p", 2), ("TTL", "p"), ("INCRBY", "p", 2))
    assert vals == [2, -1, 4]
    c.command("EXPIRE", "p", 1)
    time.sleep(1.2)
    assert c.command("GET", "p") is None


# ---------------------------------------------------------------------------
# Counter backend parity + shared-window semantics
# ---------------------------------------------------------------------------


def test_counter_backend_parity(resp):
    _, c = resp
    redis_b, mem_b = RedisCounterBackend(c), MemoryCounterBackend()
    ops = [("a", 1), ("b", 5), ("a", 2), ("c", 10), ("a", 1)]
    for key, amt in ops:
        assert redis_b.incr(key, amt, 60) == mem_b.incr(key, amt, 60)
    for key in ("a", "b", "c", "missing"):
        assert redis_b.get(key) == mem_b.get(key)


def test_rate_limiter_over_redis(resp):
    _, c = resp
    rl = RateLimiter(RedisCounterBackend(c))
    rl.do_limit("ns", "u", "m", {"rpm": 1})
    out = rl.check_limit("ns", "u", "m", {"rpm": 1}, {})
    assert out[0].over and out[0].current == 1
    # Window keys carry the wall-clock window start (fixed-window parity).
    assert str(int(time.time() // 60) * 60) in window_key("ns", "u", "m", "rpm")


def test_two_limiters_share_one_window(resp):
    """The HA property: limiters in two gateway replicas consume ONE
    budget, not one each."""
    srv, _ = resp
    a = RateLimiter(RedisCounterBackend(RespClient(srv.host, srv.port)))
    b = RateLimiter(RedisCounterBackend(RespClient(srv.host, srv.port)))
    a.do_limit("ns", "u", "m", {"rpm": 1})
    b.do_limit("ns", "u", "m", {"rpm": 1})
    assert a.check_limit("ns", "u", "m", {"rpm": 3}, {})[0].current == 2
    assert b.check_limit("ns", "u", "m", {"rpm": 2}, {})[0].over


# ---------------------------------------------------------------------------
# Quota service parity + sharing
# ---------------------------------------------------------------------------


def test_quota_service_parity_and_sharing(resp):
    srv, c = resp
    rq = RedisQuotaService(c)
    mq = QuotaService()
    for q in (rq, mq):
        q.incr_usage("ns", "qa", {"prompt": 10, "response": 5, "total": 15})
        q.incr_usage("ns", "qa", {"total": 5})
    assert rq.get_usage("ns", "qa") == mq.get_usage("ns", "qa")
    assert rq.check("ns", "qa", {"total": 20}) == mq.check("ns", "qa", {"total": 20})
    assert rq.check("ns", "qa", {"total": 21}) == mq.check("ns", "qa", {"total": 21})
    rq.set_usage("ns", "qa", "total", 3)
    assert rq.get_usage("ns", "qa")["total"] == 3

    # A second service instance (second gateway) sees the same ledger.
    rq2 = RedisQuotaService(RespClient(srv.host, srv.port))
    assert rq2.get_usage("ns", "qa")["total"] == 3


# ---------------------------------------------------------------------------
# Two full gateways sharing one store — end to end
# ---------------------------------------------------------------------------


def _mk_gateway(store, srv):
    client = RespClient(srv.host, srv.port)
    gw = Gateway(store, host="127.0.0.1", port=0, quota_sync_s=60,
                 rate_limiter=RateLimiter(RedisCounterBackend(client)),
                 quota=RedisQuotaService(client))
    gw.start(background=True)
    return gw


def test_two_gateways_share_rate_limit(resp):
    srv, _ = resp
    store = Store()
    store.create(res.Endpoint(name="m1", namespace="t", spec={}, status={
        "routes": [{"backend": {"addresses": ["127.0.0.1:9"]}, "weight": 1}]}))
    store.create(res.Token(name="bob", namespace="t", spec={
        "token": "sk-bob",
        "qos": [{"endpoint": {"name": "m1"},
                 "rateLimits": [{"type": "rpm", "value": 2}]}]}))
    gw1, gw2 = _mk_gateway(store, srv), _mk_gateway(store, srv)
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline and not (
            gw1.qos.token_known("sk-bob") and gw2.qos.token_known("sk-bob")):
        time.sleep(0.02)

    def post(gw):
        req = urllib.request.Request(
            f"http://127.0.0.1:{gw.port}/v1/chat/completions",
            data=json.dumps({"model": "m1",
                             "messages": [{"role": "user", "content": "x"}]}).encode(),
            headers={"Content-Type": "application/json",
                     "Authorization": "Bearer sk-bob"})
        try:
            urllib.request.urlopen(req, timeout=10)
            return 200
        except urllib.error.HTTPError as e:
            return e.code

    try:
        # rpm=2 TOTAL across both replicas: the first two admissions consume
        # the shared window (the dead backend turns them into 502s — past
        # admission), the third is 429 no matter which replica it hits.
        assert post(gw1) in (502, 503)
        assert post(gw2) in (502, 503)
        assert post(gw1) == 429
        assert post(gw2) == 429
    finally:
        gw1.stop()
        gw2.stop()


# ---------------------------------------------------------------------------
# Cluster + sentinel topologies (reference cmd/gateway/main.go:137-170)
# ---------------------------------------------------------------------------


def test_key_slot_crc16_and_hashtags():
    from arks_tpu.gateway.rediskv import key_slot

    # Known CRC16-XMODEM vectors from the Redis Cluster spec.
    assert key_slot("123456789") == 0x31C3 % 16384
    assert key_slot("{user1000}.following") == key_slot("{user1000}.followers")
    # Empty first tag => the WHOLE key hashes (spec rule), so the later
    # {bar} tag must NOT be used.
    from arks_tpu.gateway.rediskv import _crc16
    assert key_slot("foo{}{bar}") == _crc16(b"foo{}{bar}") % 16384
    assert key_slot("foo{}{bar}") != key_slot("bar")


def test_cluster_client_follows_moved_redirects():
    from arks_tpu.gateway.rediskv import (
        RespClusterClient, RespServer, key_slot)

    a, b = RespServer(), RespServer()
    a.start()
    b.start()
    try:
        key = "arks:quota:namespace=d:quotaname=q:type=total"
        # Node A disowns the key's slot and points at B.
        a.moved_slots[key_slot(key)] = f"127.0.0.1:{b.port}"
        client = RespClusterClient([("127.0.0.1", a.port)])
        client.command("SET", key, 41)
        assert int(client.command("INCRBY", key, 1)) == 42
        # The MOVED mapping stuck: the value lives on B only.
        from arks_tpu.gateway.rediskv import RespClient
        direct_b = RespClient("127.0.0.1", b.port)
        assert direct_b.command("GET", key) == b"42"
        direct_a_val = None  # A never stored it (it redirected)
        client.close()
        direct_b.close()
    finally:
        a.stop()
        b.stop()


def test_cluster_client_bootstraps_slot_map():
    """CLUSTER SLOTS at construction routes keys to the right node on the
    FIRST try — no MOVED round trip — and records every master as a
    failover candidate."""
    from arks_tpu.gateway.rediskv import (
        RespClusterClient, RespServer, key_slot)

    a, b = RespServer(), RespServer()
    a.start()
    b.start()
    try:
        key = "arks:quota:namespace=d:quotaname=q:type=total"
        slot = key_slot(key)
        topo = [(0, slot - 1, "127.0.0.1", a.port),
                (slot, 16383, "127.0.0.1", b.port)]
        a.cluster_slots.extend(topo)
        b.cluster_slots.extend(topo)
        client = RespClusterClient([("127.0.0.1", a.port)])
        assert client._slots[slot] == ("127.0.0.1", b.port)
        assert ("127.0.0.1", b.port) in client._nodes
        client.command("SET", key, 7)
        # Straight to B — A (which would MOVED-redirect via moved_slots)
        # never saw the key.
        from arks_tpu.gateway.rediskv import RespClient
        direct_b = RespClient("127.0.0.1", b.port)
        assert direct_b.command("GET", key) == b"7"
        direct_b.close()
        client.close()
    finally:
        a.stop()
        b.stop()


def test_cluster_client_fails_over_when_default_node_dies():
    """Losing the seed/default node must not strand commands for
    not-yet-learned slots: the client drops the dead node, re-points at a
    survivor, relearns the topology, and retries (ADVICE r3)."""
    from arks_tpu.gateway.rediskv import (
        RespClusterClient, RespServer)

    a, b = RespServer(), RespServer()
    a.start()
    b.start()
    try:
        topo = [(0, 16383, "127.0.0.1", b.port)]
        # A knows the topology; B owns every slot.
        a.cluster_slots.extend(topo)
        b.cluster_slots.extend(topo)
        client = RespClusterClient([("127.0.0.1", a.port)])
        a.stop()
        # Keyless commands route to the default (dead A) — the failover
        # path must retry them on B.
        assert client.command("PING") == "PONG"
        assert client.command("SET", "k", "1") == "OK"
        assert client.command("GET", "k") == b"1"
        client.close()
    finally:
        b.stop()


def test_cluster_backend_parity_with_single():
    """The rate-limit/quota backends behave identically over a cluster
    client with redirects and over a single-node client."""
    from arks_tpu.gateway.rediskv import (
        RedisCounterBackend, RespClient, RespClusterClient, RespServer,
        key_slot)

    a, b = RespServer(), RespServer()
    a.start()
    b.start()
    try:
        key = "arks:rl:ns=d:user=u:model=m:rpm:12345"
        a.moved_slots[key_slot(key)] = f"127.0.0.1:{b.port}"
        cluster = RedisCounterBackend(RespClusterClient([("127.0.0.1", a.port)]))
        single_srv = RespServer()
        single_srv.start()
        single = RedisCounterBackend(
            RespClient("127.0.0.1", single_srv.port))
        for backend in (cluster, single):
            assert backend.get(key) == 0
            assert backend.incr(key, 3, ttl_s=60) == 3
            assert backend.incr(key, 2, ttl_s=60) == 5
            assert backend.get(key) == 5
        single_srv.stop()
    finally:
        a.stop()
        b.stop()


def test_sentinel_client_resolves_and_refollows_master():
    from arks_tpu.gateway.rediskv import (
        RespServer, SentinelRespClient)

    master1, master2, sentinel = RespServer(), RespServer(), RespServer()
    for s in (master1, master2, sentinel):
        s.start()
    try:
        sentinel.sentinel_masters["mymaster"] = ("127.0.0.1", master1.port)
        client = SentinelRespClient([("127.0.0.1", sentinel.port)],
                                    "mymaster")
        client.command("SET", "k", "v1")
        assert client.command("GET", "k") == b"v1"
        # Failover: sentinel now points at master2; killing master1 forces
        # a reconnect, which re-resolves through the sentinel.
        sentinel.sentinel_masters["mymaster"] = ("127.0.0.1", master2.port)
        master1.stop()
        client.command("SET", "k", "v2")
        assert client.command("GET", "k") == b"v2"
        client.close()
    finally:
        for s in (master2, sentinel):
            s.stop()


def test_make_resp_client_topology_selection():
    from arks_tpu.gateway.rediskv import (
        RespClient, RespClusterClient, RespServer, SentinelRespClient,
        make_resp_client)

    a, b = RespServer(), RespServer()
    a.start()
    b.start()
    try:
        single = make_resp_client(f"127.0.0.1:{a.port}")
        assert type(single) is RespClient
        cluster = make_resp_client(
            f"127.0.0.1:{a.port},127.0.0.1:{b.port}")
        assert type(cluster) is RespClusterClient
        a.sentinel_masters["m"] = ("127.0.0.1", b.port)
        sent = make_resp_client(f"127.0.0.1:{a.port}", sentinel_master="m")
        assert type(sent) is SentinelRespClient
        assert (sent.host, sent.port) == ("127.0.0.1", b.port)
        for c in (single, cluster, sent):
            c.close()
    finally:
        a.stop()
        b.stop()
