"""Pipelined decode (ARKS_PIPELINE_DEPTH): token-exact parity vs the
sequential issue/resolve path at depths 1-3, mid-stream aborts, stop-token
overshoot truncation, slot-reuse-after-overshoot KV correctness, multihost
follower replay of the pipelined op stream, and the emit-stream depth
bound."""

import numpy as np
import pytest

from arks_tpu.engine import EngineConfig, InferenceEngine, Request, SamplingParams
from arks_tpu.engine.tokenizer import ByteTokenizer
from arks_tpu.models import get_config


class RecordingDispatcher:
    def __init__(self):
        self.ops = []

    def broadcast(self, op, payload):
        self.ops.append((op, payload))


def _mk_engine(monkeypatch, depth, mixed="0", **kw):
    monkeypatch.setenv("ARKS_PIPELINE_DEPTH", str(depth))
    monkeypatch.setenv("ARKS_MIXED_STEP", mixed)
    cfg = get_config("tiny")
    defaults = dict(model="tiny", num_slots=2, max_cache_len=64,
                    prefill_buckets=(8, 16, 32), steps_per_dispatch=4)
    defaults.update(kw)
    eng = InferenceEngine(cfg, EngineConfig(**defaults), ByteTokenizer())
    if depth and isinstance(depth, int) and depth > 0:
        # Deterministic engagement: serving warms the pipe programs in the
        # background and stays sequential meanwhile; tests wait so short
        # workloads can't finish before the pipelined path opens.
        assert eng._pipe_warm_wait(300) == "ready"
    return cfg, eng


def _collect(req, timeout=120):
    ids, lps, fin = [], [], None
    while True:
        out = req.outputs.get(timeout=timeout)
        ids.extend(out.token_ids)
        if out.logprobs:
            lps.extend(out.logprobs)
        if out.finished:
            fin = out
            break
    return ids, lps, fin


def _drive(engine, n_steps=800):
    for _ in range(n_steps):
        engine.step(block_s=0.01)
        if (engine.num_running == 0 and engine._queue.empty()
                and not engine._prefilling):
            break


def _run_workload(monkeypatch, depth, mixed="0", **kw):
    """Greedy + fixed-seed sampled + logprob requests with slot churn
    (more requests than slots); returns each request's full output."""
    cfg, eng = _mk_engine(monkeypatch, depth, mixed, **kw)
    assert eng._pipe_depth == (depth if depth >= 0 else 0)
    prompts = [[5, 6, 7], list(range(3, 23)), [9] * 5, [4] * 12, [8, 3]]
    reqs = []
    for i, p in enumerate(prompts):
        sp = SamplingParams(max_tokens=9,
                            temperature=0.0 if i % 2 == 0 else 0.8,
                            top_p=0.9, top_k=40, seed=7 + i, ignore_eos=True,
                            logprobs=2 if i == 2 else None)
        reqs.append(Request(f"r{i}", [int(x) % cfg.vocab_size for x in p], sp))
    for r in reqs:
        eng.add_request(r)
    _drive(eng)
    return [_collect(r) for r in reqs], eng


@pytest.mark.parametrize("mixed,kw", [
    ("0", {}),
    ("auto", dict(prefill_chunk=16, kv_layout="paged")),
])
def test_pipeline_token_parity_depths(monkeypatch, mixed, kw):
    """Depths 1/2/3 must produce BYTE-IDENTICAL streams (tokens, logprobs,
    finish reasons) to the sequential path (depth 0), on both the legacy
    slot engine and the mixed paged engine."""
    base, _ = _run_workload(monkeypatch, 0, mixed, **kw)
    for depth in (1, 2, 3):
        got, eng = _run_workload(monkeypatch, depth, mixed, **kw)
        assert got == base, f"depth {depth} diverged from sequential"
        # The pipelined path actually ran (occupancy histogram observed).
        occ = eng.metrics.pipeline_depth_occupancy._data
        assert occ, "pipelined path never engaged"


def test_pipeline_one_dispatch_per_iteration_and_depth_bound(monkeypatch):
    """Emit-stream contract: in steady state exactly ONE model dispatch is
    issued per scheduler iteration, and the advertised occupancy never
    exceeds ARKS_PIPELINE_DEPTH."""
    depth = 2
    cfg, eng = _mk_engine(monkeypatch, depth)
    eng.dispatcher = RecordingDispatcher()
    r = Request("p0", [5, 6, 7], SamplingParams(
        max_tokens=40, temperature=0.0, ignore_eos=True))
    eng.add_request(r)
    per_step = []
    for _ in range(400):
        before = sum(1 for op, _ in eng.dispatcher.ops if op == "decode_pipe")
        eng.step(block_s=0.01)
        after = sum(1 for op, _ in eng.dispatcher.ops if op == "decode_pipe")
        per_step.append(after - before)
        if eng.num_running == 0 and eng._queue.empty():
            break
    _collect(r)
    pipe_ops = [p for op, p in eng.dispatcher.ops if op == "decode_pipe"]
    assert pipe_ops, "no pipelined dispatches on the emit stream"
    assert max(per_step) == 1, "more than one pipelined dispatch per step"
    occs = [p["occupancy"] for p in pipe_ops]
    assert max(occs) <= depth, occs
    assert depth in occs, "pipeline never filled to the configured depth"
    # Exactly the first dispatch of the run carries fresh host state.
    assert pipe_ops[0]["fresh"] is True
    assert all(not p["fresh"] for p in pipe_ops[1:])


def test_pipeline_midstream_abort(monkeypatch):
    """An abort raised while dispatches are in flight drains the pipeline
    and frees the slot; the engine keeps serving afterwards."""
    cfg, eng = _mk_engine(monkeypatch, 2)
    victim = Request("v", [5, 6, 7], SamplingParams(
        max_tokens=10_000, temperature=0.0, ignore_eos=True))
    eng.add_request(victim)
    for _ in range(50):
        eng.step(block_s=0.01)
        if eng._pipe_inflight:
            break
    assert eng._pipe_inflight, "pipeline never engaged"
    eng.abort("v")
    _drive(eng)
    ids, _, fin = _collect(victim)
    assert fin.finish_reason == "abort"
    assert not eng._pipe_inflight and eng._pipe_state is None
    # Slot is reusable: a fresh request completes normally.
    nxt = Request("n", [9, 9], SamplingParams(
        max_tokens=4, temperature=0.0, ignore_eos=True))
    eng.add_request(nxt)
    _drive(eng)
    ids2, _, fin2 = _collect(nxt)
    assert fin2.finish_reason == "length" and len(ids2) == 4


def _greedy_probe(monkeypatch, prompt, n):
    _, eng = _mk_engine(monkeypatch, 0)
    r = Request("probe", prompt, SamplingParams(
        max_tokens=n, temperature=0.0, ignore_eos=True))
    eng.add_request(r)
    _drive(eng)
    ids, _, _ = _collect(r)
    return ids


def test_pipeline_stop_overshoot_truncation(monkeypatch):
    """A stop token landing mid-dispatch with further dispatches in flight:
    the stream truncates at the stop exactly like the sequential path, and
    the <= depth*K overshoot tokens are discarded."""
    probe = _greedy_probe(monkeypatch, [5, 6, 7], 16)
    stop = probe[9]  # lands mid-dispatch (K=4) with the pipeline deep

    def run(depth):
        _, eng = _mk_engine(monkeypatch, depth)
        r = Request("s", [5, 6, 7], SamplingParams(
            max_tokens=64, temperature=0.0, ignore_eos=True,
            stop_token_ids=(int(stop),)))
        eng.add_request(r)
        _drive(eng)
        return _collect(r)

    base = run(0)
    for depth in (2, 3):
        assert run(depth) == base
    ids, _, fin = base
    assert fin.finish_reason == "stop"
    assert int(stop) not in ids  # stop token itself excluded from output


def test_pipeline_slot_reuse_after_overshoot(monkeypatch):
    """After a request dies mid-run (overshoot KV rows written past its
    stop in its pages/rows), the SAME slot must serve the next request
    with correct attention — the reclaimed rows are garbage until real
    prefill/decode overwrites them.  num_slots=1 forces reuse; paged
    layout exercises page reclaim."""
    probe = _greedy_probe(monkeypatch, [5, 6, 7], 12)
    stop = probe[5]

    def run(depth, reuse_first):
        _, eng = _mk_engine(monkeypatch, depth, mixed="auto", num_slots=1,
                            prefill_chunk=16, kv_layout="paged")
        outs = []
        if reuse_first:
            a = Request("a", [5, 6, 7], SamplingParams(
                max_tokens=64, temperature=0.0, ignore_eos=True,
                stop_token_ids=(int(stop),)))
            eng.add_request(a)
            _drive(eng)
            outs.append(_collect(a))
        b = Request("b", list(range(3, 21)), SamplingParams(
            max_tokens=8, temperature=0.0, ignore_eos=True))
        eng.add_request(b)
        _drive(eng)
        outs.append(_collect(b))
        return outs

    # b's stream through a reused slot (garbage overshoot rows reclaimed)
    # must equal b's stream on a fresh engine, at every depth.
    fresh = run(2, reuse_first=False)[-1]
    for depth in (0, 1, 2, 3):
        got = run(depth, reuse_first=True)
        assert got[-1] == fresh, f"slot reuse corrupted stream at depth {depth}"
        assert got[0][2].finish_reason == "stop"


def test_pipeline_follower_replay(monkeypatch):
    """A follower fed the leader's recorded op stream replays the
    pipelined dispatches from its OWN threaded device state (no host token
    values on the wire) and converges to the leader's exact device state."""
    from arks_tpu.engine.multihost import DispatchFollower

    cfg, leader = _mk_engine(monkeypatch, 2, mixed="auto",
                             prefill_chunk=16, kv_layout="paged")
    leader.dispatcher = RecordingDispatcher()
    reqs = []
    for i, p in enumerate([[5, 6, 7], list(range(3, 23)), [9] * 5]):
        sp = SamplingParams(max_tokens=6,
                            temperature=0.0 if i % 2 == 0 else 0.8,
                            seed=11 + i, ignore_eos=True)
        reqs.append(Request(f"f{i}", p, sp))
        leader.add_request(reqs[-1])
    _drive(leader)
    for r in reqs:
        _collect(r)
    ops = leader.dispatcher.ops
    pipe_ops = [p for op, p in ops if op == "decode_pipe"]
    assert pipe_ops, "no pipelined ops on the channel"
    # Pipelined ops carry NO token values except the run-opening fresh one.
    assert all(("tokens" in p) == bool(p["fresh"]) for p in pipe_ops)

    import jax
    import jax.numpy as jnp

    _, feng = _mk_engine(monkeypatch, 2, mixed="auto",
                         prefill_chunk=16, kv_layout="paged")
    follower = DispatchFollower.__new__(DispatchFollower)
    follower.engine = feng
    follower._jax = jax
    follower._pipe_state = None
    follower._pipe_cols = None
    for op, payload in ops:
        follower._apply(feng, jax, jnp, op, payload)
    # Lockstep invariant: identical op replay -> identical device state.
    np.testing.assert_array_equal(np.asarray(leader._cache.k),
                                  np.asarray(feng._cache.k))
    np.testing.assert_array_equal(np.asarray(leader._sampling.key),
                                  np.asarray(feng._sampling.key))


def test_pipeline_survives_parked_guide_compile(monkeypatch):
    """A request parked on a slow guide compile is pure host bookkeeping:
    it must NOT drain the pipeline (live decoding would degrade to the
    sequential path for the whole compile window); once the guide
    publishes, the request admits and its output obeys the grammar."""
    import threading
    import time as _time

    cfg, eng = _mk_engine(monkeypatch, 2, mixed="auto",
                          prefill_chunk=16, kv_layout="paged",
                          max_cache_len=96)
    eng.dispatcher = RecordingDispatcher()
    load = Request("load", [5, 6, 7], SamplingParams(
        max_tokens=400, temperature=0.0, ignore_eos=True))
    eng.add_request(load)

    def pipe_ops():
        return sum(1 for op, _ in eng.dispatcher.ops if op == "decode_pipe")

    for _ in range(100):
        eng.step(block_s=0.01)
        if pipe_ops():
            break
    assert pipe_ops(), "pipeline never engaged"

    release = threading.Event()
    orig = eng.guides._build

    def gated_build(rx):
        release.wait(30)
        return orig(rx)

    eng.guides._build = gated_build
    greq = Request("g", [9, 9], SamplingParams(
        max_tokens=24, temperature=0.0, guide=("regex", r"ab+a")))
    eng.add_request(greq)
    deadline = _time.monotonic() + 2.0
    while _time.monotonic() < deadline and not eng._awaiting_guide:
        eng.step(block_s=0.01)
    assert eng._awaiting_guide, "guided request never parked"
    # Parked compile in flight: every iteration keeps issuing pipelined
    # dispatches (no degradation to the sequential path).
    before = pipe_ops()
    for _ in range(10):
        eng.step(block_s=0.01)
    assert eng._awaiting_guide, "guide published before the gate opened"
    assert pipe_ops() - before >= 10, \
        "parked guide compile knocked decoding off the pipelined path"
    release.set()
    _drive(eng, n_steps=2000)
    ids, _, fin = _collect(greq)
    assert fin.finish_reason == "stop"
    import re
    assert re.fullmatch(r"ab+a", ByteTokenizer().decode(ids))
    _, _, lfin = _collect(load)
    assert lfin.finish_reason == "length"


def test_pipeline_enabled_for_spec_engines(monkeypatch):
    """Speculative engines PIPELINE (the spec_pipe program threads
    accepted-length/last-token state on device): the env depth sticks and
    the per-slot write margin is the draft_len verify block.
    Byte-identity across depths is asserted in
    tests/test_spec_decode.py::test_pipeline_depth_parity."""
    monkeypatch.setenv("ARKS_PIPELINE_DEPTH", "2")
    cfg = get_config("tiny")
    ecfg = EngineConfig(model="tiny", num_slots=2, max_cache_len=64,
                        prefill_buckets=(8, 16, 32), steps_per_dispatch=4,
                        prefill_chunk=16, kv_layout="paged",
                        draft_model="tiny", draft_len=3)
    eng = InferenceEngine(cfg, ecfg, ByteTokenizer())
    assert eng._pipe_depth == 2
    assert eng.resolved_config["pipeline_depth"] == "2"
    assert eng._pipe_rows == 3


def test_pipeline_env_validation(monkeypatch):
    monkeypatch.setenv("ARKS_PIPELINE_DEPTH", "bogus")
    cfg = get_config("tiny")
    with pytest.raises(ValueError, match="ARKS_PIPELINE_DEPTH"):
        InferenceEngine(cfg, EngineConfig(model="tiny", num_slots=2,
                                          max_cache_len=64,
                                          prefill_buckets=(8, 16, 32)),
                        ByteTokenizer())


def test_pipeline_oversized_stop_set_falls_back(monkeypatch):
    """A request whose stop set exceeds the device column keeps the engine
    on the sequential path (stream still correct, never truncated)."""
    from arks_tpu.engine import sampler as sampler_mod

    big_stops = tuple(range(100, 100 + sampler_mod.STOP_IDS_MAX + 4))

    def run(depth):
        _, eng = _mk_engine(monkeypatch, depth)
        r = Request("big", [5, 6, 7], SamplingParams(
            max_tokens=8, temperature=0.0, ignore_eos=True,
            stop_token_ids=big_stops))
        eng.add_request(r)
        _drive(eng)
        return _collect(r), eng

    base, _ = run(0)
    got, eng = run(2)
    assert got == base
    # The oversized stop set kept the pipeline cold.
    assert not eng.metrics.pipeline_depth_occupancy._data
