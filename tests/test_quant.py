"""Weight-only quantization (models.quant): int8 (w8a16) and int4 (w4a16)
numerics, engine wiring, sharded equivalence.

Reference parity note: the reference has no quantization code (dtype flags
pass through runtimeCommonArgs to vLLM/SGLang); w8a16/w4a16 here are the
TPU-native mechanisms that fit 7B-class (int8) and 13B-class (int4) models
on one 16GB v5e chip (BASELINE.md north-star config).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from arks_tpu.models import get_config, quant
from arks_tpu.models import transformer as tf
from arks_tpu.parallel.mesh import make_mesh


def _rel_err(a, b):
    a, b = np.asarray(a, np.float64), np.asarray(b, np.float64)
    return np.abs(a - b).max() / (np.abs(b).max() + 1e-9)


def test_quantize_tensor_roundtrip():
    w = jax.random.normal(jax.random.PRNGKey(0), (64, 32), jnp.float32) * 0.02
    qt = quant.quantize_tensor(w, axis=-2)
    assert qt["q"].dtype == jnp.int8 and qt["s"].shape == (1, 32)
    deq = quant.dequantize(qt, jnp.float32)
    # Symmetric 8-bit: worst-case error is half a step (~amax/254 per column).
    assert _rel_err(deq, w) < 1.0 / 200


def test_qeinsum_matches_dense_matmul():
    k = jax.random.PRNGKey(1)
    x = jax.random.normal(k, (4, 64), jnp.float32)
    w = jax.random.normal(jax.random.PRNGKey(2), (64, 32), jnp.float32) * 0.05
    ref = jnp.einsum("be,ef->bf", x, w)
    got = quant.qeinsum("be,ef->bf", x, quant.quantize_tensor(w))
    assert _rel_err(got, ref) < 0.02


@pytest.mark.parametrize("name", ["tiny", "tiny-gqa"])
def test_quantized_forward_close_to_full(name):
    cfg = get_config(name)
    params = tf.init_params(cfg, jax.random.PRNGKey(0), jnp.float32)
    qparams = quant.quantize_params(params)
    toks = jax.random.randint(jax.random.PRNGKey(1), (2, 12), 0, cfg.vocab_size)
    lengths = jnp.asarray([12, 12], jnp.int32)

    ref, rks, rvs = tf.prefill(params, cfg, toks, lengths)
    got, qks, qvs = tf.prefill(qparams, cfg, toks, lengths)
    # Logits drift accumulates over layers; top-1 agreement + bounded error
    # is the serving-relevant criterion.
    assert _rel_err(got, ref) < 0.1
    np.testing.assert_array_equal(np.argmax(np.asarray(got), -1),
                                  np.argmax(np.asarray(ref), -1))

    # Decode path runs (shape + finiteness) and matches full-width top-1.
    cache = tf.init_cache(cfg, num_slots=2, max_len=32, dtype=jnp.float32)
    cache = tf.insert(cache, qks, qvs, jnp.asarray(0))
    lengths_d = jnp.zeros((2,), jnp.int32).at[0].set(12)
    logits_d, _ = tf.decode_step(qparams, cfg, cache, jnp.zeros((2,), jnp.int32),
                                 lengths_d)
    assert np.isfinite(np.asarray(logits_d)).all()


def test_quantized_moe_forward():
    cfg = get_config("tiny-moe")
    params = tf.init_params(cfg, jax.random.PRNGKey(0), jnp.float32)
    qparams = quant.quantize_params(params)
    # Router must stay full-width (softmax-sensitive).
    assert not quant.is_quantized(qparams["layers"]["router"])
    assert quant.is_quantized(qparams["layers"]["w_gate"])
    toks = jax.random.randint(jax.random.PRNGKey(1), (1, 80), 0, cfg.vocab_size)
    lengths = jnp.asarray([80], jnp.int32)
    ref, _, _ = tf.prefill(params, cfg, toks, lengths)   # grouped path (T>=64)
    got, _, _ = tf.prefill(qparams, cfg, toks, lengths)
    assert _rel_err(got, ref) < 0.15


def test_quantized_sharded_matches_unsharded():
    cfg = get_config("tiny-gqa")
    params = tf.init_params(cfg, jax.random.PRNGKey(0), jnp.float32)
    qparams = quant.quantize_params(params)
    toks = jax.random.randint(jax.random.PRNGKey(1), (2, 8), 0, cfg.vocab_size)
    lengths = jnp.asarray([8, 8], jnp.int32)
    ref, _, _ = tf.prefill(qparams, cfg, toks, lengths)

    mesh = make_mesh(tensor_parallel=4, data_parallel=2,
                     devices=jax.devices()[:8])
    qsharded = tf.shard_params(qparams, cfg, mesh)
    got, _, _ = tf.prefill(qsharded, cfg, toks, lengths, mesh)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=5e-4, atol=5e-4)


def test_engine_weight_dtype_int8():
    from arks_tpu.engine import EngineConfig, InferenceEngine, Request, SamplingParams
    from arks_tpu.engine.tokenizer import ByteTokenizer
    cfg = get_config("tiny")
    ecfg = EngineConfig(model="tiny", num_slots=2, max_cache_len=64,
                        prefill_buckets=(16, 32), weight_dtype="int8")
    eng = InferenceEngine(cfg, ecfg, ByteTokenizer())
    assert quant.is_quantized(eng.params["layers"]["wq"])
    req = Request("q1", [5, 6, 7], SamplingParams(max_tokens=4, temperature=0.0,
                                                  ignore_eos=True))
    eng.add_request(req)
    for _ in range(50):
        eng.step(block_s=0.01)
        if eng.num_running == 0 and eng._queue.empty():
            break
    out, ids = None, []
    while out is None or not out.finished:
        out = req.outputs.get(timeout=30)
        ids.extend(out.token_ids)
    assert len(ids) == 4


def test_quantize_tensor_int4_roundtrip():
    """w4a16 groupwise: int4 payload + [K/G, N] group scales; bounded
    error (worst case half a step = amax/14 per group-channel)."""
    w = jax.random.normal(jax.random.PRNGKey(0), (256, 32), jnp.float32) * 0.02
    qt = quant.quantize_tensor_int4(w, group=64)
    assert qt["q"].dtype == jnp.int4
    assert qt["gs"].shape == (4, 32)
    deq = quant.dequantize(qt, jnp.float32)
    assert _rel_err(deq, w) < 1.0 / 12


def test_qeinsum_int4_matches_dequant_exactly():
    """The fused qeinsum path must equal einsum against the materialized
    dequantized weight bit-for-bit (same math, different fusion)."""
    x = jax.random.normal(jax.random.PRNGKey(1), (4, 256), jnp.float32)
    w = jax.random.normal(jax.random.PRNGKey(2), (256, 32), jnp.float32) * 0.05
    qt = quant.quantize_tensor_int4(w, group=128)
    got = quant.qeinsum("be,ef->bf", x, qt)
    ref = jnp.einsum("be,ef->bf", x, quant.dequantize(qt, jnp.float32))
    np.testing.assert_array_equal(np.asarray(got), np.asarray(ref))
    # And it approximates the dense matmul (int4's per-element error is
    # ~amax/14, so output-relative error sits near 0.1 on random
    # normals — the model-level tests assert the serving-relevant
    # criterion, top-1 agreement).
    dense = jnp.einsum("be,ef->bf", x, w)
    assert _rel_err(got, dense) < 0.15


@pytest.mark.parametrize("name", ["tiny", "tiny-gqa"])
def test_int4_forward_close_to_full(name):
    """w4a16 prefill: bounded drift vs full width, top-1 agreement (the
    embedding stays int8, matmuls go int4 groupwise)."""
    cfg = get_config(name)
    params = tf.init_params(cfg, jax.random.PRNGKey(0), jnp.float32)
    qparams = quant.quantize_params(params, bits=4)
    assert "gs" in qparams["layers"]["wq"]          # int4 matmul leaves
    assert "s" in qparams["embed"]                  # embedding stays int8
    toks = jax.random.randint(jax.random.PRNGKey(1), (2, 12), 0, cfg.vocab_size)
    lengths = jnp.asarray([12, 12], jnp.int32)
    ref, _, _ = tf.prefill(params, cfg, toks, lengths)
    got, _, _ = tf.prefill(qparams, cfg, toks, lengths)
    assert _rel_err(got, ref) < 0.2
    # Tiny random models have near-uniform logits, so exact top-1 equality
    # is noise-sensitive at 4 bits: assert the full-width argmax stays in
    # the int4 top-3 per row instead.
    ref_top1 = np.argmax(np.asarray(ref), -1)
    got_top3 = np.argsort(np.asarray(got), -1)[..., -3:]
    assert all(t in row for t, row in
               zip(ref_top1.ravel(), got_top3.reshape(-1, 3)))


def test_int4_sharded_matches_unsharded():
    cfg = get_config("tiny-gqa")
    params = tf.init_params(cfg, jax.random.PRNGKey(0), jnp.float32)
    # group 16: whole groups per model-axis shard of the tiny dims (the
    # sharded contraction dims are 64 wide over tp=4 -> local K 16).
    qparams = quant.quantize_params(params, bits=4, group=16)
    toks = jax.random.randint(jax.random.PRNGKey(1), (2, 8), 0, cfg.vocab_size)
    lengths = jnp.asarray([8, 8], jnp.int32)
    ref, _, _ = tf.prefill(qparams, cfg, toks, lengths)

    mesh = make_mesh(tensor_parallel=4, data_parallel=2,
                     devices=jax.devices()[:8])
    qsharded = tf.shard_params(qparams, cfg, mesh)
    got, _, _ = tf.prefill(qsharded, cfg, toks, lengths, mesh)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=5e-4, atol=5e-4)


def test_int4_moe_forward():
    """int4 expert weights take the ragged_dot path (the Pallas kernel's
    fused dequant is int8-only) and stay close to full width."""
    cfg = get_config("tiny-moe")
    params = tf.init_params(cfg, jax.random.PRNGKey(0), jnp.float32)
    qparams = quant.quantize_params(params, bits=4)
    toks = jax.random.randint(jax.random.PRNGKey(1), (2, 8), 0, cfg.vocab_size)
    lengths = jnp.asarray([8, 8], jnp.int32)
    ref, _, _ = tf.prefill(params, cfg, toks, lengths)
    got, _, _ = tf.prefill(qparams, cfg, toks, lengths)
    assert _rel_err(got, ref) < 0.25


def test_engine_weight_dtype_int4():
    from arks_tpu.engine import EngineConfig, InferenceEngine, Request, SamplingParams
    from arks_tpu.engine.tokenizer import ByteTokenizer
    cfg = get_config("tiny")
    ecfg = EngineConfig(model="tiny", num_slots=2, max_cache_len=64,
                        prefill_buckets=(16, 32), weight_dtype="int4")
    eng = InferenceEngine(cfg, ecfg, ByteTokenizer())
    assert "gs" in eng.params["layers"]["wq"]
    assert eng.resolved_config["weight_dtype"] == "int4"
    req = Request("q4", [5, 6, 7], SamplingParams(max_tokens=4, temperature=0.0,
                                                  ignore_eos=True))
    eng.add_request(req)
    for _ in range(80):
        eng.step(block_s=0.01)
        if eng.num_running == 0 and eng._queue.empty():
            break
    out, ids = None, []
    while out is None or not out.finished:
        out = req.outputs.get(timeout=30)
        ids.extend(out.token_ids)
    assert len(ids) == 4
    assert all(0 <= t < cfg.vocab_size for t in ids)
