"""Compile-budget regression guard (tier-1).

The mixed scheduler collapses the (bucket, M, lp) admit-program family
into one budget-shaped program.  This test runs a mixed workload —
admissions of several lengths + chunked prefill + decode — and asserts the
number of DISTINCT jitted program variants stays under a declared budget,
so a future scheduler edit that silently reintroduces per-shape retraces
(or a dtype/weak-type wobble that doubles every program) fails CI instead
of surfacing as TPU compile stalls in production.
"""

import json

from arks_tpu.engine import EngineConfig, InferenceEngine, Request, SamplingParams
from arks_tpu.engine.tokenizer import ByteTokenizer
from arks_tpu.models import get_config

# One mixed program + its logprob twin, set_slot/clear_penalties state
# writes, and the handful of single-shape helpers the engine always jits.
# The point is the ORDER of magnitude: the legacy scheduler's admit family
# alone is len(buckets) x len(admit_sizes) x 2 programs.
MIXED_TOTAL_BUDGET = 14
MIXED_PER_PROGRAM_BUDGET = 2  # lp twins are separate jit objects already


def _drain(req, timeout=120):
    while True:
        out = req.outputs.get(timeout=timeout)
        if out.finished:
            return out


def test_mixed_workload_compile_variant_budget(monkeypatch):
    monkeypatch.setenv("ARKS_MIXED_STEP", "auto")
    cfg = get_config("tiny")
    ecfg = EngineConfig(model="tiny", num_slots=4, max_cache_len=64,
                        prefill_buckets=(8, 16, 32), steps_per_dispatch=4,
                        prefill_chunk=16, kv_layout="paged")
    eng = InferenceEngine(cfg, ecfg, ByteTokenizer())
    assert eng._mixed

    # Admissions of several lengths (one-shot-sized AND chunk-length),
    # logprobs on/off, sampled and greedy, plus decode churn.
    prompts = [[5, 6], [3] * 12, [7] * 20, list(range(3, 51)), [9] * 30,
               [4] * 5, [8] * 17]
    reqs = []
    for i, p in enumerate(prompts):
        sp = SamplingParams(
            max_tokens=4,
            temperature=0.0 if i % 2 == 0 else 0.7,
            seed=i, ignore_eos=True,
            logprobs=1 if i == 1 else None)
        reqs.append(Request(f"cb{i}", [int(x) % cfg.vocab_size for x in p],
                            sp))
    for r in reqs:
        eng.add_request(r)
    for _ in range(600):
        eng.step(block_s=0.01)
        if (eng.num_running == 0 and eng._queue.empty()
                and not eng._prefilling):
            break
    for r in reqs:
        assert _drain(r).finished

    variants = eng.compiled_program_variants()
    assert variants, "no jitted programs discovered on the engine"
    total = sum(variants.values())
    assert total <= MIXED_TOTAL_BUDGET, variants
    for name, n in variants.items():
        assert n <= MIXED_PER_PROGRAM_BUDGET, (name, variants)
    # The admit family must not have compiled at all: mixed mode routes
    # every prompt through the chunked path.
    assert variants.get("_admit_fn", 0) == 0, variants
    assert variants.get("_admit_lp_fn", 0) == 0, variants
    # The mixed program itself is ONE variant per lp flavor.
    assert variants.get("_mixed_fn", 0) == 1, variants
    assert variants.get("_mixed_lp_fn", 0) <= 1, variants


# Spec engines add the draft-prefill program (one per bucket) and the
# spec-mixed program pair on top of the mixed engine's set; the point is
# that draft+verify is ONE budget-shaped program per lp flavor — no
# per-draft-len/per-batch verify family, no fused-loop twins.
SPEC_TOTAL_BUDGET = 18


def test_spec_workload_compile_variant_budget(monkeypatch):
    """The spec program family collapsed into the mixed family: a spec
    workload (several prompt lengths, greedy + sampled + logprobs +
    penalized — enabled AND disabled lanes) compiles exactly one
    spec-mixed program per lp flavor, no legacy decode/admit variants."""
    monkeypatch.setenv("ARKS_MIXED_STEP", "auto")
    cfg = get_config("tiny")
    ecfg = EngineConfig(model="tiny", num_slots=4, max_cache_len=64,
                        prefill_buckets=(8, 16, 32), steps_per_dispatch=4,
                        prefill_chunk=16, kv_layout="paged",
                        draft_model="tiny-gqa", draft_len=4,
                        prefix_cache_mb=0)
    eng = InferenceEngine(cfg, ecfg, ByteTokenizer())
    assert eng._mixed

    prompts = [[5, 6], [3] * 12, [7] * 20, list(range(3, 51)), [9] * 30]
    reqs = []
    for i, p in enumerate(prompts):
        sp = SamplingParams(
            max_tokens=4,
            temperature=0.0 if i % 2 == 0 else 0.7,
            seed=i, ignore_eos=True,
            logprobs=1 if i == 1 else None,
            frequency_penalty=0.5 if i == 2 else 0.0)
        reqs.append(Request(f"sb{i}", [int(x) % cfg.vocab_size for x in p],
                            sp))
    for r in reqs:
        eng.add_request(r)
    for _ in range(600):
        eng.step(block_s=0.01)
        if (eng.num_running == 0 and eng._queue.empty()
                and not eng._prefilling):
            break
    for r in reqs:
        assert _drain(r).finished
    assert eng._spec_proposed > 0

    variants = eng.compiled_program_variants()
    assert sum(variants.values()) <= SPEC_TOTAL_BUDGET, variants
    # ONE spec-mixed program per lp flavor — the whole point: verify
    # lanes are just ragged rows of the mixed dispatch, so there is no
    # per-K (or per-enable-mask) recompile family.
    assert variants.get("_spec_mixed_fn", 0) == 1, variants
    assert variants.get("_spec_mixed_lp_fn", 0) <= 1, variants
    # The legacy families are gone/dark.
    assert variants.get("_decode_fn", 0) == 0, variants
    assert variants.get("_admit_fn", 0) == 0, variants
    assert "_spec_fn" not in variants, variants


def test_ragged_kernel_family_budget_with_tuned_cache(monkeypatch, tmp_path):
    """The ragged mixed kernel family under a CACHED autotune entry: the
    tuned block_q must flow from the table into the resolved plan and the
    jitted kernel launcher (_paged_mixed_call) must compile exactly ONE
    variant for the whole mixed workload — a tuned entry swaps the statics'
    VALUES, it must never add a compiled variant next to the default, and
    the engine-level budget is unchanged from the dense-era census."""
    from arks_tpu.ops import autotune, paged_attention
    from arks_tpu.models import transformer as tf

    cache = tmp_path / "kernel_tune.json"
    monkeypatch.setenv("ARKS_KERNEL_TUNE", "cached")
    monkeypatch.setenv("ARKS_KERNEL_TUNE_CACHE", str(cache))
    monkeypatch.setenv("ARKS_ATTN_IMPL", "pallas")
    monkeypatch.setenv("ARKS_MIXED_GRID", "ragged")
    monkeypatch.setenv("ARKS_MIXED_STEP", "1")
    autotune.invalidate_cache()

    cfg = get_config("tiny")
    ecfg = EngineConfig(model="tiny", num_slots=2, max_cache_len=64,
                        prefill_buckets=(8, 16, 32), steps_per_dispatch=4,
                        prefill_chunk=16, kv_layout="paged",
                        prefix_cache_mb=0)
    eng = InferenceEngine(cfg, ecfg, ByteTokenizer())
    assert eng._mixed and eng._paged

    # Seed the tune table for the engine's own mixed signature with a
    # NON-default block_q (the heuristic would pick min(qmax, 32)).
    sig = autotune.mixed_signature(
        hkv=cfg.num_kv_heads, g=cfg.num_heads // cfg.num_kv_heads,
        d=tf.cache_head_dim(cfg, eng._pad_head()), page=eng._page_size(),
        qmax=eng._mixed_budget + 1, kv=str(eng._cache.k.dtype))
    autotune.record("paged_mixed", sig, {"block_q": 8, "dma_depth": 2})
    autotune.invalidate_cache()  # force the load path, not the write-through
    assert json.loads(cache.read_text())  # the entry persisted

    kernel_before = paged_attention._paged_mixed_call._cache_size()
    reqs = [Request(f"rk{i}", [int(x) % cfg.vocab_size for x in p],
                    SamplingParams(max_tokens=3, temperature=0.0,
                                   ignore_eos=True))
            for i, p in enumerate([[5, 6, 7], [3] * 12, [9] * 20])]
    for r in reqs:
        eng.add_request(r)
    for _ in range(600):
        eng.step(block_s=0.01)
        if (eng.num_running == 0 and eng._queue.empty()
                and not eng._prefilling):
            break
    for r in reqs:
        assert _drain(r).finished

    # The tuned entry reached the resolved plan (counters memoize it).
    plan = eng._grid_plans[eng._mixed_budget + 1]
    assert plan["block_q"] == 8 and plan["grid"] == "ragged", plan
    # Inside the engine the launcher is INLINED into the jitted step
    # programs — its own cache must not have grown (no stray eager launch
    # escaped the step programs).
    assert paged_attention._paged_mixed_call._cache_size() == kernel_before
    # Engine-level census unchanged from the dense-grid era.
    variants = eng.compiled_program_variants()
    assert sum(variants.values()) <= MIXED_TOTAL_BUDGET, variants
    assert variants.get("_mixed_fn", 0) == 1, variants


def test_mixed_kernel_launcher_variant_census(monkeypatch, tmp_path):
    """Kernel-family census at the launcher itself (direct calls, where
    _paged_mixed_call owns its jit cache): repeated calls reuse one
    variant; an autotune entry matching the heuristic's choice adds ZERO
    variants (the table swaps static VALUES, it is not a second code
    path); only a genuinely different tuned block_q compiles one more."""
    import jax.numpy as jnp
    import numpy as np

    from arks_tpu.ops import autotune
    from arks_tpu.ops import paged_attention as pa

    cache = tmp_path / "kernel_tune.json"
    monkeypatch.setenv("ARKS_KERNEL_TUNE", "cached")
    monkeypatch.setenv("ARKS_KERNEL_TUNE_CACHE", str(cache))
    monkeypatch.setenv("ARKS_MIXED_GRID", "ragged")
    autotune.invalidate_cache()

    l, s, hkv, g, maxp, page, d, qmax = 1, 2, 1, 1, 2, 8, 8, 4
    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.normal(size=(s, hkv, g, qmax, d)), jnp.float32)
    kp = jnp.asarray(rng.normal(size=(l, s * maxp, hkv, page, d)), jnp.float32)
    vp = jnp.asarray(rng.normal(size=kp.shape), jnp.float32)
    tables = jnp.arange(s * maxp, dtype=jnp.int32).reshape(s, maxp)
    pos = jnp.array([3, 0], jnp.int32)
    qlen = jnp.array([2, 4], jnp.int32)

    def launch():
        out = pa.paged_mixed_attention(q, kp, vp, tables, pos, qlen, 0,
                                       interpret=True)
        return np.asarray(out)

    before = pa._paged_mixed_call._cache_size()
    launch()
    assert pa._paged_mixed_call._cache_size() == before + 1
    launch()  # same resolved plan -> cache hit
    assert pa._paged_mixed_call._cache_size() == before + 1

    sig = autotune.mixed_signature(hkv=hkv, g=g, d=d, page=page, qmax=qmax,
                                   kv="float32")
    # Entry matching the heuristic (block_q = min(qmax, 32) = qmax): the
    # cached table must round-trip into the SAME compiled variant.
    autotune.record("paged_mixed", sig, {"block_q": qmax, "dma_depth": 2})
    autotune.invalidate_cache()
    launch()
    assert pa._paged_mixed_call._cache_size() == before + 1
    # A genuinely different tuned block_q is one more variant, exactly.
    autotune.record("paged_mixed", sig, {"block_q": 2, "dma_depth": 2})
    autotune.invalidate_cache()
    launch()
    assert pa._paged_mixed_call._cache_size() == before + 2
