"""Compile-budget regression guard (tier-1).

The mixed scheduler collapses the (bucket, M, lp) admit-program family
into one budget-shaped program.  This test runs a mixed workload —
admissions of several lengths + chunked prefill + decode — and asserts the
number of DISTINCT jitted program variants stays under a declared budget,
so a future scheduler edit that silently reintroduces per-shape retraces
(or a dtype/weak-type wobble that doubles every program) fails CI instead
of surfacing as TPU compile stalls in production.
"""

from arks_tpu.engine import EngineConfig, InferenceEngine, Request, SamplingParams
from arks_tpu.engine.tokenizer import ByteTokenizer
from arks_tpu.models import get_config

# One mixed program + its logprob twin, set_slot/clear_penalties state
# writes, and the handful of single-shape helpers the engine always jits.
# The point is the ORDER of magnitude: the legacy scheduler's admit family
# alone is len(buckets) x len(admit_sizes) x 2 programs.
MIXED_TOTAL_BUDGET = 14
MIXED_PER_PROGRAM_BUDGET = 2  # lp twins are separate jit objects already


def _drain(req, timeout=120):
    while True:
        out = req.outputs.get(timeout=timeout)
        if out.finished:
            return out


def test_mixed_workload_compile_variant_budget(monkeypatch):
    monkeypatch.setenv("ARKS_MIXED_STEP", "auto")
    cfg = get_config("tiny")
    ecfg = EngineConfig(model="tiny", num_slots=4, max_cache_len=64,
                        prefill_buckets=(8, 16, 32), steps_per_dispatch=4,
                        prefill_chunk=16, kv_layout="paged")
    eng = InferenceEngine(cfg, ecfg, ByteTokenizer())
    assert eng._mixed

    # Admissions of several lengths (one-shot-sized AND chunk-length),
    # logprobs on/off, sampled and greedy, plus decode churn.
    prompts = [[5, 6], [3] * 12, [7] * 20, list(range(3, 51)), [9] * 30,
               [4] * 5, [8] * 17]
    reqs = []
    for i, p in enumerate(prompts):
        sp = SamplingParams(
            max_tokens=4,
            temperature=0.0 if i % 2 == 0 else 0.7,
            seed=i, ignore_eos=True,
            logprobs=1 if i == 1 else None)
        reqs.append(Request(f"cb{i}", [int(x) % cfg.vocab_size for x in p],
                            sp))
    for r in reqs:
        eng.add_request(r)
    for _ in range(600):
        eng.step(block_s=0.01)
        if (eng.num_running == 0 and eng._queue.empty()
                and not eng._prefilling):
            break
    for r in reqs:
        assert _drain(r).finished

    variants = eng.compiled_program_variants()
    assert variants, "no jitted programs discovered on the engine"
    total = sum(variants.values())
    assert total <= MIXED_TOTAL_BUDGET, variants
    for name, n in variants.items():
        assert n <= MIXED_PER_PROGRAM_BUDGET, (name, variants)
    # The admit family must not have compiled at all: mixed mode routes
    # every prompt through the chunked path.
    assert variants.get("_admit_fn", 0) == 0, variants
    assert variants.get("_admit_lp_fn", 0) == 0, variants
    # The mixed program itself is ONE variant per lp flavor.
    assert variants.get("_mixed_fn", 0) == 1, variants
    assert variants.get("_mixed_lp_fn", 0) <= 1, variants


# Spec engines add the draft-prefill program (one per bucket) and the
# spec-mixed program pair on top of the mixed engine's set; the point is
# that draft+verify is ONE budget-shaped program per lp flavor — no
# per-draft-len/per-batch verify family, no fused-loop twins.
SPEC_TOTAL_BUDGET = 18


def test_spec_workload_compile_variant_budget(monkeypatch):
    """The spec program family collapsed into the mixed family: a spec
    workload (several prompt lengths, greedy + sampled + logprobs +
    penalized — enabled AND disabled lanes) compiles exactly one
    spec-mixed program per lp flavor, no legacy decode/admit variants."""
    monkeypatch.setenv("ARKS_MIXED_STEP", "auto")
    cfg = get_config("tiny")
    ecfg = EngineConfig(model="tiny", num_slots=4, max_cache_len=64,
                        prefill_buckets=(8, 16, 32), steps_per_dispatch=4,
                        prefill_chunk=16, kv_layout="paged",
                        draft_model="tiny-gqa", draft_len=4,
                        prefix_cache_mb=0)
    eng = InferenceEngine(cfg, ecfg, ByteTokenizer())
    assert eng._mixed

    prompts = [[5, 6], [3] * 12, [7] * 20, list(range(3, 51)), [9] * 30]
    reqs = []
    for i, p in enumerate(prompts):
        sp = SamplingParams(
            max_tokens=4,
            temperature=0.0 if i % 2 == 0 else 0.7,
            seed=i, ignore_eos=True,
            logprobs=1 if i == 1 else None,
            frequency_penalty=0.5 if i == 2 else 0.0)
        reqs.append(Request(f"sb{i}", [int(x) % cfg.vocab_size for x in p],
                            sp))
    for r in reqs:
        eng.add_request(r)
    for _ in range(600):
        eng.step(block_s=0.01)
        if (eng.num_running == 0 and eng._queue.empty()
                and not eng._prefilling):
            break
    for r in reqs:
        assert _drain(r).finished
    assert eng._spec_proposed > 0

    variants = eng.compiled_program_variants()
    assert sum(variants.values()) <= SPEC_TOTAL_BUDGET, variants
    # ONE spec-mixed program per lp flavor — the whole point: verify
    # lanes are just ragged rows of the mixed dispatch, so there is no
    # per-K (or per-enable-mask) recompile family.
    assert variants.get("_spec_mixed_fn", 0) == 1, variants
    assert variants.get("_spec_mixed_lp_fn", 0) <= 1, variants
    # The legacy families are gone/dark.
    assert variants.get("_decode_fn", 0) == 0, variants
    assert variants.get("_admit_fn", 0) == 0, variants
    assert "_spec_fn" not in variants, variants
