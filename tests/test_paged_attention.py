"""Paged-KV Pallas kernels vs XLA oracles (interpret mode on CPU).

The compiled-TPU counterpart rides bench.py's parity hook; here the same
math runs in interpret mode so CPU CI exercises the kernel bodies."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from arks_tpu.ops.attention import decode_attention_xla, _decode_attention_xla_quant
from arks_tpu.ops.paged_attention import (
    build_mixed_work_list,
    mixed_grid_plan,
    pack_int4,
    paged_decode_attention,
    paged_gather_kv,
    paged_kv_update,
    paged_kv_update_quant,
    paged_mixed_attention,
    paged_update_xla,
    unpack_int4,
)


def _setup(l=2, b=4, hkv=2, g=3, n=None, max_pages=4, page=16, d=32,
           quantized=False, seed=0):
    """Random pool + disjoint per-slot tables + ragged lengths."""
    n = n or b * max_pages + 2
    key = jax.random.PRNGKey(seed)
    ks = jax.random.split(key, 8)
    if quantized:
        kp = jax.random.randint(ks[0], (l, n, hkv, page, d), -127, 128, jnp.int8)
        vp = jax.random.randint(ks[1], (l, n, hkv, page, d), -127, 128, jnp.int8)
        kps = jax.random.uniform(ks[4], (l, n, hkv, page), jnp.float32, 0.01, 0.03)
        vps = jax.random.uniform(ks[5], (l, n, hkv, page), jnp.float32, 0.01, 0.03)
    else:
        kp = jax.random.normal(ks[0], (l, n, hkv, page, d), jnp.float32)
        vp = jax.random.normal(ks[1], (l, n, hkv, page, d), jnp.float32)
        kps = vps = None
    q = jax.random.normal(ks[2], (b, hkv, g, d), jnp.float32)
    # Distinct pages per (slot, page-index): a permutation of pool indices.
    perm = jax.random.permutation(ks[3], n)[: b * max_pages]
    tables = perm.reshape(b, max_pages).astype(jnp.int32)
    lengths = jnp.asarray(
        [1 + (i * 7919) % (max_pages * page - 1) for i in range(b)], jnp.int32)
    return q, kp, vp, kps, vps, tables, lengths


@pytest.mark.parametrize("quantized", [False, True])
@pytest.mark.parametrize("block_b", [1, 2, 4])
def test_paged_attention_matches_oracle(quantized, block_b):
    page = 128 if quantized else 16
    q, kp, vp, kps, vps, tables, lengths = _setup(
        quantized=quantized, page=page)
    for layer in (0, 1):
        out = paged_decode_attention(
            q, kp, vp, tables, lengths, layer, k_scale=kps, v_scale=vps,
            block_b=block_b, interpret=True)
        kc = paged_gather_kv(kp, tables, layer)
        vc = paged_gather_kv(vp, tables, layer)
        if quantized:
            ksc = paged_gather_kv(kps, tables, layer)
            vsc = paged_gather_kv(vps, tables, layer)
            ref = _decode_attention_xla_quant(q, kc, vc, ksc, vsc, lengths)
        else:
            ref = decode_attention_xla(q, kc, vc, lengths)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-2 if quantized else 2e-5,
                                   rtol=2e-2 if quantized else 2e-5)


def test_paged_attention_shared_pages():
    """Two slots sharing prefix pages read identical prefixes (the whole
    point of paging: zero-copy sharing)."""
    q, kp, vp, _, _, tables, _ = _setup(b=2, max_pages=4, page=16)
    q = jnp.concatenate([q[:1], q[:1]])          # same query
    shared = tables.at[1, :2].set(tables[0, :2])  # share first 2 pages
    lengths = jnp.asarray([32, 32], jnp.int32)    # both end inside page 2
    out = paged_decode_attention(q, kp, vp, shared, lengths, 0,
                                 block_b=1, interpret=True)
    np.testing.assert_allclose(np.asarray(out[0]), np.asarray(out[1]),
                               atol=1e-6)


def test_paged_update_matches_oracle():
    q, kp, vp, _, _, tables, lengths = _setup(page=16)
    b, hkv, d = 4, 2, 32
    key = jax.random.PRNGKey(9)
    kn = jax.random.normal(key, (b, hkv, d), jnp.float32)
    vn = jax.random.normal(jax.random.fold_in(key, 1), (b, hkv, d), jnp.float32)
    for layer in (0, 1):
        got_k, got_v = paged_kv_update(kp, vp, kn, vn, lengths, tables,
                                       layer, interpret=True)
        ref_k, ref_v, _, _ = paged_update_xla(
            kp, vp, None, None, kn, vn, lengths, tables, layer)
        np.testing.assert_allclose(np.asarray(got_k), np.asarray(ref_k))
        np.testing.assert_allclose(np.asarray(got_v), np.asarray(ref_v))


def test_paged_update_quant_matches_oracle():
    q, kp, vp, kps, vps, tables, lengths = _setup(quantized=True, page=128)
    b, hkv, d = 4, 2, 32
    key = jax.random.PRNGKey(11)
    kn = jax.random.normal(key, (b, hkv, d), jnp.float32)
    vn = jax.random.normal(jax.random.fold_in(key, 1), (b, hkv, d), jnp.float32)
    got = paged_kv_update_quant(kp, vp, kps, vps, kn, vn, lengths, tables,
                                1, interpret=True)
    ref = paged_update_xla(kp, vp, kps, vps, kn, vn, lengths, tables, 1)
    for g, r in zip(got, ref):
        np.testing.assert_allclose(np.asarray(g), np.asarray(r))


def test_paged_update_out_of_range_dropped():
    """write_idx beyond the table's coverage must not corrupt the pool."""
    _, kp, vp, _, _, tables, _ = _setup(page=16)
    b, hkv, d = 4, 2, 32
    kn = jnp.ones((b, hkv, d), jnp.float32)
    vn = jnp.ones((b, hkv, d), jnp.float32)
    idx = jnp.full((b,), 4 * 16, jnp.int32)  # == max_pages * page
    got_k, got_v = paged_kv_update(kp, vp, kn, vn, idx, tables, 0,
                                   interpret=True)
    np.testing.assert_allclose(np.asarray(got_k), np.asarray(kp))
    np.testing.assert_allclose(np.asarray(got_v), np.asarray(vp))


# ---------------------------------------------------------------------------
# Ragged mixed-query kernel (prefill chunks + decode lanes in one grid)
# ---------------------------------------------------------------------------


def _mixed_ref(q, kp, vp, kps, vps, tables, pos_start, q_len, layer):
    """Oracle: per-(sequence, query) masked attention over gathered pages —
    query i of sequence s attends positions [0, pos_start[s]+i]."""
    kc = paged_gather_kv(kp, tables, layer)
    vc = paged_gather_kv(vp, tables, layer)
    out = np.zeros(np.asarray(q).shape, np.float32)
    for s in range(q.shape[0]):
        for i in range(int(q_len[s])):
            lens = jnp.asarray([int(pos_start[s]) + i + 1], jnp.int32)
            if kps is not None:
                ksc = paged_gather_kv(kps, tables, layer)
                vsc = paged_gather_kv(vps, tables, layer)
                ref = _decode_attention_xla_quant(
                    q[s:s + 1, :, :, i], kc[s:s + 1], vc[s:s + 1],
                    ksc[s:s + 1], vsc[s:s + 1], lens)
            else:
                ref = decode_attention_xla(q[s:s + 1, :, :, i],
                                           kc[s:s + 1], vc[s:s + 1], lens)
            out[s, :, :, i] = np.asarray(ref[0], np.float32)
    return out


@pytest.mark.parametrize("quantized", [False, True])
@pytest.mark.parametrize("block_q", [2, 4, 8])
def test_paged_mixed_attention_matches_oracle(quantized, block_q):
    """Ragged q_len parity vs the XLA oracle: q_len = 1 (a decode lane),
    a partial chunk, a full chunk, and an inactive lane — the shapes the
    mixed scheduler actually dispatches — with SHARED prefix pages."""
    page = 128 if quantized else 16
    q, kp, vp, kps, vps, tables, _ = _setup(quantized=quantized, page=page)
    b, hkv, g, d = q.shape
    qmax = 8
    key = jax.random.PRNGKey(3)
    qm = jax.random.normal(key, (b, hkv, g, qmax, d), jnp.float32)
    # Slot 1 shares slot 0's first page (prefix reuse): its queries read
    # the shared prefix through its own table.
    tables = tables.at[1, 0].set(tables[0, 0])
    pos_start = jnp.asarray([5, page, 0, 3], jnp.int32)
    q_len = jnp.asarray([1, qmax, 3, 0], jnp.int32)
    for layer in (0, 1):
        out = paged_mixed_attention(qm, kp, vp, tables, pos_start, q_len,
                                    layer, k_scale=kps, v_scale=vps,
                                    block_q=block_q, interpret=True)
        ref = _mixed_ref(qm, kp, vp, kps, vps, tables, pos_start, q_len,
                         layer)
        for s in range(b):
            for i in range(int(q_len[s])):
                np.testing.assert_allclose(
                    np.asarray(out[s, :, :, i], np.float32), ref[s, :, :, i],
                    atol=2e-2 if quantized else 2e-5,
                    rtol=2e-2 if quantized else 2e-5)


@pytest.mark.parametrize("quantized", [False, True])
def test_paged_mixed_attention_verify_rows_match_oracle(quantized):
    """Speculative verify as ragged rows: mixed batches carrying q_len=K
    verify blocks ALONGSIDE q_len=1 decode lanes and chunk rows — the
    exact shape a spec-mixed dispatch sends — including a verify block
    that CROSSES a page boundary, on bf16 and int8-quantized pools."""
    page = 128 if quantized else 16
    q, kp, vp, kps, vps, tables, _ = _setup(quantized=quantized, page=page)
    b, hkv, g, d = q.shape
    K = 4
    qmax = 8
    qm = jax.random.normal(jax.random.PRNGKey(7), (b, hkv, g, qmax, d),
                           jnp.float32)
    # Lane 0: q_len=1 decode row.  Lane 1: q_len=K verify block CROSSING
    # the page boundary (starts K//2 before the page edge).  Lane 2:
    # q_len=K verify block inside page 0.  Lane 3: a chunk row span.
    pos_start = jnp.asarray([5, page - K // 2, 2, 0], jnp.int32)
    q_len = jnp.asarray([1, K, K, qmax], jnp.int32)
    for layer in (0, 1):
        out = paged_mixed_attention(qm, kp, vp, tables, pos_start, q_len,
                                    layer, k_scale=kps, v_scale=vps,
                                    block_q=4, interpret=True)
        ref = _mixed_ref(qm, kp, vp, kps, vps, tables, pos_start, q_len,
                         layer)
        for s in range(b):
            for i in range(int(q_len[s])):
                np.testing.assert_allclose(
                    np.asarray(out[s, :, :, i], np.float32),
                    ref[s, :, :, i],
                    atol=2e-2 if quantized else 2e-5,
                    rtol=2e-2 if quantized else 2e-5)


@pytest.mark.parametrize("quantized", [False, True])
def test_mixed_step_verify_rows_match_verify_step(quantized):
    """Model-level closure: a spec-mixed flat batch's verify-block logits
    (tf.mixed_step with q_len=K rows) match tf.verify_step — the retired
    dedicated verify dispatch, kept as the oracle — on the same paged
    pool, with one block crossing a page boundary."""
    from arks_tpu.models import get_config, transformer as tf

    cfg = get_config("tiny")
    params = tf.init_params(cfg, jax.random.PRNGKey(0), jnp.float32)
    B, K, PAGE, MAXP = 2, 4, 16, 4
    pool_a = tf.init_paged_cache(cfg, B * MAXP, PAGE, jnp.float32,
                                 quantized=quantized)
    pool_b = tf.init_paged_cache(cfg, B * MAXP, PAGE, jnp.float32,
                                 quantized=quantized)
    tables = jnp.arange(B * MAXP, dtype=jnp.int32).reshape(B, MAXP)
    # Slot 1's block crosses the page boundary (14 -> 18 with page 16).
    lengths = jnp.asarray([3, PAGE - 2], jnp.int32)
    key = jax.random.PRNGKey(2)
    for slot in range(B):
        plen = int(lengths[slot])
        pk = jax.random.normal(jax.random.fold_in(key, slot),
                               (cfg.num_layers, 1, plen, cfg.num_kv_heads,
                                cfg.head_dim), jnp.float32)
        pv = pk * 0.5 + 1.0
        n_pages = -(-plen // PAGE)
        pad = n_pages * PAGE - plen
        pkp = jnp.pad(pk, ((0, 0), (0, 0), (0, pad), (0, 0), (0, 0)))
        pvp = jnp.pad(pv, ((0, 0), (0, 0), (0, pad), (0, 0), (0, 0)))
        pool_a = tf.insert_pages(pool_a, pkp, pvp, tables[slot],
                                 jnp.asarray(n_pages))
        pool_b = tf.insert_pages(pool_b, pkp, pvp, tables[slot],
                                 jnp.asarray(n_pages))
    blocks = jax.random.randint(jax.random.PRNGKey(5), (B, K), 2, 200,
                                jnp.int32)
    ref, pool_a = tf.verify_step(params, cfg, pool_a, blocks, lengths,
                                 tables=tables)
    # The same blocks as a spec-mixed flat batch: lane b owns rows
    # [b*K, (b+1)*K); logits gathered at every row.
    flat_tokens = blocks.reshape(-1)
    flat_slot = jnp.repeat(jnp.arange(B, dtype=jnp.int32), K)
    flat_pos = (lengths[:, None]
                + jnp.arange(K, dtype=jnp.int32)[None, :]).reshape(-1)
    src = jnp.arange(B * K, dtype=jnp.int32)
    got, pool_b = tf.mixed_step(
        params, cfg, pool_b, tables, flat_tokens, flat_slot, flat_pos,
        src, jnp.arange(B, dtype=jnp.int32) * K,
        jnp.full((B,), K, jnp.int32), lengths)
    got = got.reshape(B, K, -1)
    tol = 2e-2 if quantized else 2e-4
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               atol=tol, rtol=tol)
    # The written KV rows agree too (the next dispatch reads them).
    np.testing.assert_allclose(np.asarray(pool_b.k), np.asarray(pool_a.k),
                               atol=1e-5)


def _setup_int4(l=2, b=4, hkv=2, g=3, max_pages=4, page=128, d=32, seed=0):
    """int4 pool (packed token pairs) + the UNPACKED int8 twin for oracles."""
    n = b * max_pages + 2
    ks = jax.random.split(jax.random.PRNGKey(seed), 8)
    k8 = jax.random.randint(ks[0], (l, n, hkv, page, d), -7, 8, jnp.int8)
    v8 = jax.random.randint(ks[1], (l, n, hkv, page, d), -7, 8, jnp.int8)
    kps = jax.random.uniform(ks[4], (l, n, hkv, page), jnp.float32, 0.01, 0.03)
    vps = jax.random.uniform(ks[5], (l, n, hkv, page), jnp.float32, 0.01, 0.03)
    kp = pack_int4(k8, axis=3)
    vp = pack_int4(v8, axis=3)
    q = jax.random.normal(ks[2], (b, hkv, g, d), jnp.float32)
    perm = jax.random.permutation(ks[3], n)[: b * max_pages]
    tables = perm.reshape(b, max_pages).astype(jnp.int32)
    return q, (kp, vp), (k8, v8), kps, vps, tables


def test_pack_unpack_int4_roundtrip():
    vals = jax.random.randint(jax.random.PRNGKey(0), (2, 3, 8, 5), -7, 8,
                              jnp.int8)
    packed = pack_int4(vals, axis=2)
    assert packed.shape == (2, 3, 4, 5)
    np.testing.assert_array_equal(np.asarray(unpack_int4(packed, axis=2)),
                                  np.asarray(vals))


@pytest.mark.parametrize("block_q", [2, 4, 8])
def test_paged_mixed_attention_int4_matches_oracle(block_q):
    """int4 cells of the oracle-parity matrix: the packed pool through the
    mixed kernel equals (a) the XLA oracle on the unpacked pool and (b)
    the mixed kernel fed the unpacked int8 pool BITWISE — dequant fused on
    the page stream changes no math.  Includes a verify block crossing a
    page boundary and an inactive lane."""
    page = 128
    q, (kp, vp), (k8, v8), kps, vps, tables = _setup_int4(page=page)
    b, hkv, g, d = q.shape
    qmax = 8
    qm = jax.random.normal(jax.random.PRNGKey(3), (b, hkv, g, qmax, d),
                           jnp.float32)
    # Lane 1's rows cross the page boundary; lane 3 is inactive.
    pos_start = jnp.asarray([5, page - 2, 0, 3], jnp.int32)
    q_len = jnp.asarray([1, qmax, 3, 0], jnp.int32)
    for layer in (0, 1):
        out = paged_mixed_attention(qm, kp, vp, tables, pos_start, q_len,
                                    layer, k_scale=kps, v_scale=vps,
                                    block_q=block_q, interpret=True)
        twin = paged_mixed_attention(qm, k8, v8, tables, pos_start, q_len,
                                     layer, k_scale=kps, v_scale=vps,
                                     block_q=block_q, interpret=True)
        np.testing.assert_array_equal(np.asarray(out), np.asarray(twin))
        ref = _mixed_ref(qm, k8, v8, kps, vps, tables, pos_start, q_len,
                         layer)
        for s in range(b):
            for i in range(int(q_len[s])):
                np.testing.assert_allclose(
                    np.asarray(out[s, :, :, i], np.float32), ref[s, :, :, i],
                    atol=2e-2, rtol=2e-2)


@pytest.mark.parametrize("kv", ["f32", "int8", "int4"])
def test_ragged_and_dense_grids_byte_identical(kv):
    """The ragged work-list grid and the dense (s, qb, pages) grid share
    ONE compute body; their outputs must be bitwise identical for every
    pool dtype — the invariant the engine's stream-identity gate rides."""
    if kv == "int4":
        q, (kp, vp), _, kps, vps, tables = _setup_int4()
    else:
        q, kp, vp, kps, vps, tables, _ = _setup(
            quantized=(kv == "int8"), page=128 if kv == "int8" else 16)
    b, hkv, g, d = q.shape
    qmax = 8
    qm = jax.random.normal(jax.random.PRNGKey(5), (b, hkv, g, qmax, d),
                           jnp.float32)
    page = kps.shape[3] if kps is not None else kp.shape[3]
    pos_start = jnp.asarray([5, page - 2, 0, 3], jnp.int32)
    q_len = jnp.asarray([1, qmax, 3, 0], jnp.int32)
    kwargs = dict(k_scale=kps, v_scale=vps, block_q=4, interpret=True)
    ragged = paged_mixed_attention(qm, kp, vp, tables, pos_start, q_len, 0,
                                   grid="ragged", **kwargs)
    dense = paged_mixed_attention(qm, kp, vp, tables, pos_start, q_len, 0,
                                  grid="dense", **kwargs)
    np.testing.assert_array_equal(np.asarray(ragged), np.asarray(dense))
    # Depth is a pipelining knob, never a numerics knob.
    deep = paged_mixed_attention(qm, kp, vp, tables, pos_start, q_len, 0,
                                 grid="ragged", dma_depth=4,
                                 k_scale=kps, v_scale=vps, block_q=4,
                                 interpret=True)
    np.testing.assert_array_equal(np.asarray(ragged), np.asarray(deep))


@pytest.mark.parametrize("kv", ["f32", "int8", "int4"])
@pytest.mark.parametrize("dma_depth", [2, 4])
def test_gqa_head_grouped_kernel_byte_identical(kv, dma_depth):
    """GQA head grouping is a pure DMA-schedule change: every head_group
    divisor of hkv returns BITWISE the ungrouped ragged kernel's output
    (which is itself pinned bitwise to the dense reference above), for
    every pool dtype and DMA depth, and stays oracle-close."""
    if kv == "int4":
        q, (kp, vp), _, kps, vps, tables = _setup_int4()
    else:
        q, kp, vp, kps, vps, tables, _ = _setup(
            quantized=(kv == "int8"), page=128 if kv == "int8" else 16)
    b, hkv, g, d = q.shape
    qmax = 8
    qm = jax.random.normal(jax.random.PRNGKey(9), (b, hkv, g, qmax, d),
                           jnp.float32)
    page = kps.shape[3] if kps is not None else kp.shape[3]
    pos_start = jnp.asarray([5, page - 2, 0, 3], jnp.int32)
    q_len = jnp.asarray([1, qmax, 3, 0], jnp.int32)
    kwargs = dict(k_scale=kps, v_scale=vps, block_q=4, interpret=True,
                  grid="ragged", dma_depth=dma_depth)
    base = paged_mixed_attention(qm, kp, vp, tables, pos_start, q_len, 0,
                                 head_group=hkv, **kwargs)
    for head_group in (1, 2):
        if hkv % head_group:
            continue
        grouped = paged_mixed_attention(qm, kp, vp, tables, pos_start,
                                        q_len, 0, head_group=head_group,
                                        **kwargs)
        np.testing.assert_array_equal(np.asarray(base),
                                      np.asarray(grouped))
    dense = paged_mixed_attention(qm, kp, vp, tables, pos_start, q_len, 0,
                                  k_scale=kps, v_scale=vps, block_q=4,
                                  interpret=True, grid="dense")
    np.testing.assert_array_equal(np.asarray(base), np.asarray(dense))
    if kv == "f32":
        ref = _mixed_ref(qm, kp, vp, kps, vps, tables, pos_start, q_len, 0)
        for s in range(b):
            for i in range(int(q_len[s])):
                np.testing.assert_allclose(
                    np.asarray(base[s, :, :, i], np.float32),
                    ref[s, :, :, i], atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("kv", ["f32", "int8", "int4"])
def test_span_chained_state_matches_single_call(kv):
    """Windowed-residency building block: splitting the page loop into
    [0, split) + [split, end) spans with the f32 (m, l, acc) state carried
    between calls reproduces the single-call output BITWISE — the online
    softmax's per-page update sequence is unchanged and the final
    normalization happens exactly once, on the last span."""
    if kv == "int4":
        q, (kp, vp), _, kps, vps, tables = _setup_int4()
    else:
        q, kp, vp, kps, vps, tables, _ = _setup(
            quantized=(kv == "int8"), page=128 if kv == "int8" else 16)
    b, hkv, g, d = q.shape
    page = kps.shape[3] if kps is not None else kp.shape[3]
    # Decode-shaped lanes deep enough to span several pages each.
    qm = jax.random.normal(jax.random.PRNGKey(12), (b, hkv, g, 1, d),
                           jnp.float32)
    pos_start = jnp.asarray([3 * page + 5, 2 * page, page + 1, 3],
                            jnp.int32)
    q_len = jnp.ones((b,), jnp.int32)
    kwargs = dict(k_scale=kps, v_scale=vps, block_q=1, interpret=True,
                  grid="ragged")
    whole = paged_mixed_attention(qm, kp, vp, tables, pos_start, q_len, 0,
                                  **kwargs)
    split = jnp.full((b,), 2, jnp.int32)
    state = paged_mixed_attention(qm, kp, vp, tables, pos_start, q_len, 0,
                                  page_hi=split, emit_state=True, **kwargs)
    assert all(s.dtype == jnp.float32 for s in state)
    chained = paged_mixed_attention(qm, kp, vp, tables, pos_start, q_len,
                                    0, page_lo=split, carry_state=state,
                                    **kwargs)
    np.testing.assert_array_equal(np.asarray(whole), np.asarray(chained))


def test_mixed_all_lanes_inactive_returns_zeros():
    """q_len = 0 everywhere: the ragged work list is ALL padding (zero real
    page steps) and the output is defined — all zeros."""
    q, kp, vp, _, _, tables, _ = _setup(page=16)
    b, hkv, g, d = q.shape
    qm = jax.random.normal(jax.random.PRNGKey(1), (b, hkv, g, 4, d),
                           jnp.float32)
    zeros = jnp.zeros((b,), jnp.int32)
    out = paged_mixed_attention(qm, kp, vp, tables, jnp.zeros_like(zeros),
                                zeros, 0, interpret=True)
    np.testing.assert_array_equal(np.asarray(out), np.zeros_like(out))


def test_mixed_single_item_work_list():
    """One active lane, one q block: the smallest possible ragged grid
    still matches the oracle (and the dense grid bitwise)."""
    q, kp, vp, _, _, tables, _ = _setup(b=1, page=16)
    _, hkv, g, d = q.shape
    qm = jax.random.normal(jax.random.PRNGKey(2), (1, hkv, g, 4, d),
                           jnp.float32)
    pos_start = jnp.asarray([7], jnp.int32)
    q_len = jnp.asarray([3], jnp.int32)
    out = paged_mixed_attention(qm, kp, vp, tables, pos_start, q_len, 0,
                                block_q=4, grid="ragged", interpret=True)
    dense = paged_mixed_attention(qm, kp, vp, tables, pos_start, q_len, 0,
                                  block_q=4, grid="dense", interpret=True)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(dense))
    ref = _mixed_ref(qm, kp, vp, None, None, tables, pos_start, q_len, 0)
    for i in range(3):
        np.testing.assert_allclose(np.asarray(out[0, :, :, i], np.float32),
                                   ref[0, :, :, i], atol=2e-5, rtol=2e-5)


def test_build_mixed_work_list_compaction():
    """Real items are compacted to the grid front in (seq, qb) order with
    per-item causal page counts; padding items alias the LAST real item's
    output block (revisit semantics: no extra flush) with pages=0.  The
    (seq, qb, pages) columns are the PR 11 fixture values — the
    head-group / page-span refactor must not move them."""
    pos = jnp.asarray([5, 128, 0, 3], jnp.int32)
    qlen = jnp.asarray([1, 5, 3, 0], jnp.int32)
    seq, hg, qb, plo, pages = build_mixed_work_list(
        pos, qlen, page=128, block_q=2, num_qb=3, max_pages=3)
    seq, hg, qb, plo, pages = map(np.asarray, (seq, hg, qb, plo, pages))
    assert seq.shape == (12,)
    # Real: (0,0) 1 page; (1,0/1/2) 2 pages each; (2,0/1) 1 page each.
    np.testing.assert_array_equal(seq[:6], [0, 1, 1, 1, 2, 2])
    np.testing.assert_array_equal(qb[:6], [0, 0, 1, 2, 0, 1])
    np.testing.assert_array_equal(pages[:6], [1, 2, 2, 2, 1, 1])
    # Padding aliases the last real item, zero pages.
    np.testing.assert_array_equal(seq[6:], [2] * 6)
    np.testing.assert_array_equal(qb[6:], [1] * 6)
    np.testing.assert_array_equal(pages[6:], [0] * 6)
    # Ungrouped, unbounded defaults: hg and plo are identically zero.
    np.testing.assert_array_equal(hg, np.zeros(12, np.int32))
    np.testing.assert_array_equal(plo, np.zeros(12, np.int32))


def test_build_mixed_work_list_head_groups_and_spans():
    """head_groups replicates each real (seq, qb) item per KV head group
    (seq-major, hg, qb order) and page_lo/page_hi clamp each sequence's
    span — the windowed-residency hook.  Same PR 11 fixture inputs."""
    pos = jnp.asarray([5, 128, 0, 3], jnp.int32)
    qlen = jnp.asarray([1, 5, 3, 0], jnp.int32)
    seq, hg, qb, plo, pages = build_mixed_work_list(
        pos, qlen, page=128, block_q=2, num_qb=3, max_pages=3,
        head_groups=2,
        page_lo=jnp.asarray([0, 1, 0, 0], jnp.int32),
        page_hi=jnp.asarray([3, 2, 1, 3], jnp.int32))
    seq, hg, qb, plo, pages = map(np.asarray, (seq, hg, qb, plo, pages))
    assert seq.shape == (24,)
    # Each real item appears once per head group, hg-major inside a seq.
    np.testing.assert_array_equal(seq[:12],
                                  [0, 0, 1, 1, 1, 1, 1, 1, 2, 2, 2, 2])
    np.testing.assert_array_equal(hg[:12],
                                  [0, 1, 0, 0, 0, 1, 1, 1, 0, 0, 1, 1])
    np.testing.assert_array_equal(qb[:12],
                                  [0, 0, 0, 1, 2, 0, 1, 2, 0, 1, 0, 1])
    # seq 1's pages clamp to page_hi=2 (unchanged here) with plo=1; seq
    # 2's clamp to 1.  plo never exceeds the clamped page count.
    np.testing.assert_array_equal(pages[:12],
                                  [1, 1, 2, 2, 2, 2, 2, 2, 1, 1, 1, 1])
    np.testing.assert_array_equal(plo[:12],
                                  [0, 0, 1, 1, 1, 1, 1, 1, 0, 0, 0, 0])
    np.testing.assert_array_equal(pages[12:], np.zeros(12, np.int32))
    np.testing.assert_array_equal(plo[12:], np.zeros(12, np.int32))


def test_build_mixed_work_list_all_inactive():
    seq, hg, qb, plo, pages = build_mixed_work_list(
        jnp.zeros((3,), jnp.int32), jnp.zeros((3,), jnp.int32),
        page=16, block_q=4, num_qb=2, max_pages=4)
    np.testing.assert_array_equal(np.asarray(pages), np.zeros(6, np.int32))


def test_mixed_grid_plan_pads_awkward_qmax():
    """qmax=33 regression: the old fallback walked block_q down to the
    largest divisor (11 — a terrible tile); the plan now keeps the tuned
    block and pads the q axis instead."""
    plan = mixed_grid_plan(33, hkv=2, g=3, d=32, page=16, kv="float32")
    assert plan["block_q"] == 32
    assert plan["qpad"] == 64 and plan["num_qb"] == 2
    # And the padded grid still matches the oracle end to end.
    q, kp, vp, _, _, tables, _ = _setup(b=2, page=16)
    _, hkv, g, d = q.shape
    qm = jax.random.normal(jax.random.PRNGKey(6), (2, hkv, g, 33, d),
                           jnp.float32)
    pos_start = jnp.asarray([0, 3], jnp.int32)
    q_len = jnp.asarray([33, 1], jnp.int32)
    out = paged_mixed_attention(qm, kp, vp, tables, pos_start, q_len, 0,
                                interpret=True)
    ref = _mixed_ref(qm, kp, vp, None, None, tables, pos_start, q_len, 0)
    for s in range(2):
        for i in range(int(q_len[s])):
            np.testing.assert_allclose(
                np.asarray(out[s, :, :, i], np.float32), ref[s, :, :, i],
                atol=2e-5, rtol=2e-5)


def test_paged_update_quant_int4_matches_oracle():
    """int4 RMW update kernel vs the two-parity-pass XLA oracle: packed
    values bitwise identical; scales allclose (the jitted wrapper compiles
    amax/7 as a reciprocal multiply — 1-ULP vs the eager oracle)."""
    _, (kp, vp), _, kps, vps, tables = _setup_int4(page=128)
    b, hkv, d = 4, 2, 32
    key = jax.random.PRNGKey(11)
    kn = jax.random.normal(key, (b, hkv, d), jnp.float32)
    vn = jax.random.normal(jax.random.fold_in(key, 1), (b, hkv, d),
                           jnp.float32)
    # Odd AND even token offsets in one batch: both nibble paths taken.
    lengths = jnp.asarray([1, 2, 129, 256], jnp.int32)
    got = paged_kv_update_quant(kp, vp, kps, vps, kn, vn, lengths, tables,
                                1, interpret=True)
    ref = paged_update_xla(kp, vp, kps, vps, kn, vn, lengths, tables, 1)
    np.testing.assert_array_equal(np.asarray(got[0]), np.asarray(ref[0]))
    np.testing.assert_array_equal(np.asarray(got[1]), np.asarray(ref[1]))
    np.testing.assert_allclose(np.asarray(got[2]), np.asarray(ref[2]),
                               atol=1e-6, rtol=1e-5)
    np.testing.assert_allclose(np.asarray(got[3]), np.asarray(ref[3]),
                               atol=1e-6, rtol=1e-5)


def test_paged_mixed_attention_decode_lane_matches_decode_kernel():
    """A q_len=1 lane through the mixed kernel equals the dedicated decode
    kernel on the same pool/tables — the two paths must never diverge."""
    q, kp, vp, _, _, tables, lengths = _setup(page=16)
    b, hkv, g, d = q.shape
    qm = q[:, :, :, None, :]  # [B, Hkv, G, 1, D]
    pos_start = lengths - 1   # decode lane: query at position len-1
    q_len = jnp.ones((b,), jnp.int32)
    out = paged_mixed_attention(qm, kp, vp, tables, pos_start, q_len, 0,
                                interpret=True)
    ref = paged_decode_attention(q, kp, vp, tables, lengths, 0,
                                 block_b=1, interpret=True)
    np.testing.assert_allclose(np.asarray(out[:, :, :, 0]), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)
