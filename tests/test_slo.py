"""arks_tpu.slo: the ARKS_SLO_TIERS ladder parser and priority mapping."""

import pytest

from arks_tpu import slo


def test_parse_ladder_and_targets():
    t = slo.parse_tiers("latency:ttft_ms=300;tpot_ms=50,interactive:ttft_ms=1500,batch:")
    assert t.names == ("latency", "interactive", "batch")
    assert t.priority_of("latency") == 0
    assert t.priority_of("batch") == 2
    assert t.priority_of("nope") is None
    assert t.get("latency").ttft_ms == 300.0
    assert t.get("latency").tpot_ms == 50.0
    assert t.get("interactive").tpot_ms is None
    assert bool(t)


def test_tier_of_clamps_into_the_ladder():
    t = slo.parse_tiers("latency:,batch:")
    assert t.tier_of(0) == "latency"
    assert t.tier_of(1) == "batch"
    # Past-the-end priorities clamp to the worst tier; replayers run at
    # priority - 2**20 and clamp to the best.
    assert t.tier_of(99) == "batch"
    assert t.tier_of(-(1 << 20)) == "latency"


def test_no_ladder_means_default_label():
    t = slo.SloTiers()
    assert not t
    assert t.tier_of(0) == "default"
    assert t.tier_of(7) == "default"


@pytest.mark.parametrize("spec", [
    "latency:bogus_key=1",          # unknown target key
    "latency:ttft_ms=abc",          # non-numeric
    "latency:ttft_ms=0",            # non-positive
    "latency:,latency:",            # duplicate name
    "bad name:",                    # invalid name
])
def test_malformed_specs_rejected(spec):
    with pytest.raises(ValueError):
        slo.parse_tiers(spec)


def test_from_env(monkeypatch):
    monkeypatch.delenv(slo.ENV_VAR, raising=False)
    assert not slo.from_env()
    monkeypatch.setenv(slo.ENV_VAR, "latency:,batch:")
    assert slo.from_env().names == ("latency", "batch")
