"""Sketch-scored routing: deepest-expected-hit selection, the fallback
ladder (tie -> least-loaded -> rendezvous; stale -> rendezvous), epoch
discipline on backend restart, and the interplay with failover — sketch
scoring shapes the retry ORDER, never the failover semantics."""

import hashlib
import json
import socket
import threading
import time
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from arks_tpu import prefix_sketch as ps
from arks_tpu.router import Discovery, Router

PAGE = 4
IDS = list(range(32))                      # 8 token blocks at PAGE=4
CHAIN = ps.chain_digests(IDS, PAGE, 8)


def _payload(dev=(), host=(), epoch="e.0", page=PAGE):
    ex = ps.SketchExporter(page)
    p = ex.build(list(dev), ("k", 1), list(host), 1)
    p["epoch"] = epoch
    return p


def _body(ids=IDS):
    return json.dumps({"model": "tiny", "prompt": ids}).encode()


def _inject(router, addr, payload, age_s=0.0):
    bs = ps.BackendSketch.from_payload(payload)
    router.sketches._state[addr] = {"sketch": bs,
                                    "at": time.monotonic() - age_s}


def _mk_router(monkeypatch, decode="", prefill="", **kw):
    monkeypatch.setenv("ARKS_PREFILL_ADDRS", prefill)
    monkeypatch.setenv("ARKS_DECODE_ADDRS", decode)
    monkeypatch.setenv("ARKS_ROUTER_RETRY_BACKOFF_S", "0.01")
    # Keep the background poller inert: tests drive poll_once() directly.
    monkeypatch.setenv("ARKS_ROUTER_SKETCH_POLL_S", "60")
    return Router(Discovery(None), "tiny", host="127.0.0.1", port=0,
                  policy="cache_aware", **kw)


def _rz_order(key, backends):
    return sorted(backends, reverse=True,
                  key=lambda b: hashlib.sha1(key + b"\x00"
                                             + b.encode()).digest())


# ---------------------------------------------------------------------------
# Scoring order (white-box: _pick with injected sketches)
# ---------------------------------------------------------------------------

def test_deepest_hit_wins_and_orders_failover_candidates(monkeypatch):
    r = _mk_router(monkeypatch)
    a, b, c = "10.0.0.1:1", "10.0.0.2:1", "10.0.0.3:1"
    _inject(r, a, _payload(dev=CHAIN[:1]))
    _inject(r, b, _payload(dev=CHAIN[:3]))
    _inject(r, c, _payload())
    p, cands = r._pick(_body(), [], [a, b, c])
    assert p == ""
    assert list(cands) == [b, a, c], "deepest-first, shallower next, cold last"
    assert r.metrics.route_decisions_total.get(reason="sketch_hit") == 1
    assert r.metrics.expected_hit_blocks_total.get(
        backend=b, tier="device") == 3


def test_device_blocks_outweigh_host_blocks(monkeypatch):
    """w=1.0: two device blocks (4.0) beat three host blocks (3.0) — a
    host hit still costs the H2D restore."""
    r = _mk_router(monkeypatch)
    a, b = "10.0.0.1:1", "10.0.0.2:1"
    _inject(r, a, _payload(host=CHAIN[:3]))
    _inject(r, b, _payload(dev=CHAIN[:2]))
    _, cands = r._pick(_body(), [], [a, b])
    assert cands[0] == b
    assert r.metrics.expected_hit_blocks_total.get(
        backend=b, tier="device") == 2


def test_tie_falls_back_to_least_loaded_then_rendezvous(monkeypatch):
    r = _mk_router(monkeypatch)
    a, b = "10.0.0.1:1", "10.0.0.2:1"
    _inject(r, a, _payload(dev=CHAIN[:2]))
    _inject(r, b, _payload(dev=CHAIN[:2]))
    r._inflight = {a: 3, b: 0}
    _, cands = r._pick(_body(), [], [a, b])
    assert cands[0] == b, "tied scores: the quieter backend wins"
    assert r.metrics.route_decisions_total.get(reason="tie_fallback") == 1
    # Load tied too: rendezvous on the prefix key breaks the tie — stable.
    r._inflight = {a: 1, b: 1}
    key = json.dumps(IDS[:64]).encode()
    expect = _rz_order(key, [a, b])[0]
    for _ in range(3):
        _, cands = r._pick(_body(), [], [a, b])
        assert cands[0] == expect


def test_all_zero_scores_are_a_tie_not_a_hit(monkeypatch):
    r = _mk_router(monkeypatch)
    a, b = "10.0.0.1:1", "10.0.0.2:1"
    _inject(r, a, _payload())
    _inject(r, b, _payload())
    r._pick(_body(), [], [a, b])
    assert r.metrics.route_decisions_total.get(reason="sketch_hit") == 0
    assert r.metrics.route_decisions_total.get(reason="tie_fallback") == 1


def test_stale_or_absent_sketches_fall_back_to_rendezvous(monkeypatch):
    r = _mk_router(monkeypatch)
    a, b = "10.0.0.1:1", "10.0.0.2:1"
    # No sketches at all.
    _, cands = r._pick(_body(), [], [a, b])
    key = json.dumps(IDS[:64]).encode()
    assert list(cands) == _rz_order(key, [a, b])
    assert r.metrics.route_decisions_total.get(reason="stale_sketch") == 1
    # A sketch past the staleness deadline counts as absent (default
    # ARKS_ROUTER_SKETCH_STALE_S=10).
    _inject(r, a, _payload(dev=CHAIN[:3]), age_s=100.0)
    _, cands = r._pick(_body(), [], [a, b])
    assert list(cands) == _rz_order(key, [a, b])
    assert r.metrics.route_decisions_total.get(reason="stale_sketch") == 2


def test_promptless_body_counts_no_key(monkeypatch):
    r = _mk_router(monkeypatch)
    r._pick(json.dumps({"model": "tiny"}).encode(), [], ["10.0.0.1:1"])
    assert r.metrics.route_decisions_total.get(reason="no_key") == 1


def test_sketch_env_kill_switch(monkeypatch):
    monkeypatch.setenv("ARKS_ROUTER_SKETCH", "0")
    r = _mk_router(monkeypatch)
    assert not r.sketch_on
    a, b = "10.0.0.1:1", "10.0.0.2:1"
    _inject(r, a, _payload(dev=CHAIN[:3]))
    _, cands = r._pick(_body(), [], [a, b])
    key = json.dumps(IDS[:64]).encode()
    assert list(cands) == _rz_order(key, [a, b]), "pre-sketch rendezvous behavior"
    assert r.metrics.route_decisions_total.total() == 0


def test_multi_turn_affinity_follows_the_growing_chain(monkeypatch):
    """A conversation's prompt grows turn over turn; the sketch hit depth
    keeps the session pinned to the backend that holds its prefix even as
    other backends stay fresh (and would win rendezvous)."""
    r = _mk_router(monkeypatch)
    a, b = "10.0.0.1:1", "10.0.0.2:1"
    _inject(r, a, _payload(dev=CHAIN[:2]))
    _inject(r, b, _payload())
    history = IDS[:8]                       # turn 1: exactly the cached depth
    for turn in range(4):
        _, cands = r._pick(_body(history), [], [b, a])
        assert cands[0] == a, f"turn {turn} left its cached prefix"
        history = history + [100 + turn] * 4    # next turn grows the chain
    assert r.metrics.route_decisions_total.get(reason="sketch_hit") == 4


def test_text_domain_scoring_without_tokenizer(monkeypatch):
    """Text prompts score through the text-digest chain — no tokenizer in
    the router; the backend's alignment ledger decided what to advertise."""
    r = _mk_router(monkeypatch)
    text = "s" * 600
    ex = ps.SketchExporter(PAGE)
    tds = list(ps.iter_text_digests(text, ex.text_chars))
    assert len(tds) == 2
    # Hand-build a payload whose text-domain views cover the chain.
    toks = ps.chain_digests(list(range(8)), PAGE, 2)
    ex.link(None, [])  # no-op; ledger stays empty — link directly instead
    ex._links[tds[0]] = toks[0]
    ex._links[tds[1]] = toks[1]
    payload = ex.build(toks, ("k", 1), [], 1)
    a, b = "10.0.0.1:1", "10.0.0.2:1"
    _inject(r, a, _payload())
    _inject(r, b, payload)
    body = json.dumps({"model": "tiny", "prompt": text}).encode()
    _, cands = r._pick(body, [], [a, b])
    assert cands[0] == b
    assert r.metrics.expected_hit_blocks_total.get(
        backend=b, tier="device") == 2


# ---------------------------------------------------------------------------
# Poller + live backends
# ---------------------------------------------------------------------------

class _SketchBackend:
    """A decode backend stub serving both the scripted POST behavior of
    the failover tests and GET /v1/cache/sketch from a mutable payload."""

    def __init__(self, script, payload=None):
        backend = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt, *args):
                pass

            def _send(self, code, data, headers=()):
                self.send_response(code)
                for k, v in headers:
                    self.send_header(k, v)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def do_GET(self):
                if self.path == "/v1/cache/sketch" and backend.payload:
                    self._send(200, json.dumps(backend.payload).encode())
                else:
                    self._send(404, b"{}")

            def do_POST(self):
                self.rfile.read(int(self.headers.get("Content-Length", 0)))
                backend.last_path = self.path
                backend.last_headers = dict(self.headers)
                i = min(backend.calls, len(backend.script) - 1)
                backend.calls += 1
                if backend.script[i] == "503":
                    self._send(503, b'{"error":{"code":503}}')
                    return
                self._send(200, json.dumps(
                    {"id": "ok", "served_by": backend.name,
                     "choices": []}).encode())

        self.script = script
        self.payload = payload
        self.calls = 0
        self.last_path = None
        self.last_headers = {}
        self._httpd = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        self.addr = f"127.0.0.1:{self._httpd.server_port}"
        self.name = self.addr
        threading.Thread(target=self._httpd.serve_forever,
                         daemon=True).start()

    def stop(self):
        self._httpd.shutdown()
        self._httpd.server_close()


def _free_port_addr() -> str:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return f"127.0.0.1:{port}"


def _post(router, body):
    req = urllib.request.Request(
        f"http://127.0.0.1:{router.port}/v1/completions", data=body,
        headers={"Content-Type": "application/json"})
    return urllib.request.urlopen(req, timeout=30)


def test_poller_drops_restarted_backend_epoch(monkeypatch):
    be = _SketchBackend(["ok"], _payload(dev=CHAIN[:3], epoch="boot1.0"))
    off = _SketchBackend(["ok"], {"enabled": False})
    r = _mk_router(monkeypatch, decode=f"{be.addr},{off.addr}")
    try:
        r.sketches.poll_once()
        assert r.sketches.get(be.addr).epoch == "boot1.0"
        assert r.sketches.get(off.addr) is None, "disabled export: no sketch"
        # The backend restarts: new epoch, cold cache.  The next poll must
        # REPLACE the copy — the pre-restart membership is gone.
        be.payload = _payload(epoch="boot2.0")
        r.sketches.poll_once()
        bs = r.sketches.get(be.addr)
        assert bs.epoch == "boot2.0"
        assert bs.score_chain(CHAIN, "token") == (0, 0, 0)
        assert r.metrics.sketch_epoch_drops_total.get(backend=be.addr) == 1
        # An unreachable poll keeps the last copy (staleness retires it).
        be.stop()
        r.sketches.poll_once()
        assert r.sketches.get(be.addr).epoch == "boot2.0"
    finally:
        be.stop()
        off.stop()


def test_sketch_winner_still_fails_over_and_unified_forwarding(monkeypatch):
    """The sketch-preferred backend 503s: the request must move on to the
    next candidate exactly like pre-sketch failover — and in unified mode
    it travels the plain completion path with no prefill header."""
    win = _SketchBackend(["503"], _payload(dev=CHAIN[:4]))
    other = _SketchBackend(["ok"], _payload())
    r = _mk_router(monkeypatch, decode=f"{win.addr},{other.addr}",
                   unified=True)
    r.start(background=True)
    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{r.port}/readiness", timeout=10) as resp:
            assert json.load(resp)["status"] == "ready", \
                "unified mode is ready with decode backends only"
        r.sketches.poll_once()
        with _post(r, _body()) as resp:
            out = json.load(resp)
        assert out["served_by"] == other.name
        assert win.calls == 1, "the sketch winner was tried first"
        assert win.last_path == "/v1/completions"
        assert "X-Arks-Prefill-Addr" not in win.last_headers
        assert r.metrics.route_decisions_total.get(reason="sketch_hit") == 1
        assert r.retries_total.get(reason="backend_503") >= 1
    finally:
        r.stop()
        win.stop()
        other.stop()


def test_connection_error_invalidates_the_dead_backends_sketch(monkeypatch):
    """A restarting backend must not keep winning on its pre-restart
    sketch until the poll interval catches up: the forward path's
    connection error drops the sketch immediately."""
    dead = _free_port_addr()
    good = _SketchBackend(["ok"], _payload())
    r = _mk_router(monkeypatch, decode=f"{dead},{good.addr}", unified=True)
    r.start(background=True)
    try:
        _inject(r, dead, _payload(dev=CHAIN[:4]))
        with _post(r, _body()) as resp:
            out = json.load(resp)
        assert out["served_by"] == good.name
        assert r.retries_total.get(reason="connect_error") >= 1
        assert r.sketches.get(dead) is None, "dead backend's sketch lingered"
        # The NEXT pick no longer scores the dead backend a sketch hit.
        r._pick(_body(), [], [dead, good.addr])
        assert r.metrics.route_decisions_total.get(reason="sketch_hit") == 1, \
            "only the pre-invalidation pick may count a sketch hit"
    finally:
        r.stop()
        good.stop()
