"""Sampler correctness on CPU."""

import jax
import jax.numpy as jnp
import numpy as np

from arks_tpu.engine import sampler as sm


def _state(batch, temperature=1.0, top_p=1.0, top_k=0, seed=0,
           vocab_size=100):
    st = sm.init_sampling_state(batch, seed, vocab_size=vocab_size)
    return st._replace(
        temperature=jnp.full((batch,), temperature, jnp.float32),
        top_p=jnp.full((batch,), top_p, jnp.float32),
        top_k=jnp.full((batch,), top_k, jnp.int32))


def test_greedy_is_argmax():
    logits = jax.random.normal(jax.random.PRNGKey(0), (4, 100))
    ids, _ = sm.sample(logits, _state(4, temperature=0.0))
    np.testing.assert_array_equal(np.asarray(ids), np.argmax(np.asarray(logits), -1))


def test_top_k_1_is_argmax():
    logits = jax.random.normal(jax.random.PRNGKey(1), (4, 100))
    ids, _ = sm.sample(logits, _state(4, temperature=1.0, top_k=1))
    np.testing.assert_array_equal(np.asarray(ids), np.argmax(np.asarray(logits), -1))


def test_tiny_top_p_is_argmax():
    logits = jax.random.normal(jax.random.PRNGKey(2), (4, 100))
    ids, _ = sm.sample(logits, _state(4, temperature=1.0, top_p=1e-6))
    np.testing.assert_array_equal(np.asarray(ids), np.argmax(np.asarray(logits), -1))


def test_sampling_respects_top_k_support():
    # With top_k=3, only the 3 highest-logit ids may ever be sampled.
    logits = jnp.tile(jnp.arange(50.0)[None], (2, 1))  # argsorted: 49,48,47
    state = _state(2, temperature=5.0, top_k=3, seed=7, vocab_size=50)
    seen = set()
    for _ in range(50):
        ids, state = sm.sample(logits, state)
        seen.update(np.asarray(ids).tolist())
    assert seen <= {47, 48, 49}
    assert len(seen) > 1  # actually samples, not greedy


def test_keys_advance():
    logits = jnp.zeros((2, 64))  # uniform: successive draws should differ
    state = _state(2, temperature=1.0, vocab_size=64)
    draws = []
    for _ in range(8):
        ids, state = sm.sample(logits, state)
        draws.append(tuple(np.asarray(ids).tolist()))
    assert len(set(draws)) > 1


def test_mixed_greedy_and_sampled_slots():
    logits = jax.random.normal(jax.random.PRNGKey(3), (2, 100))
    st = _state(2, temperature=1.0, top_k=1)
    st = st._replace(temperature=jnp.asarray([0.0, 1.0], jnp.float32))
    ids, _ = sm.sample(logits, st)
    assert int(ids[0]) == int(jnp.argmax(logits[0]))


def test_presence_frequency_penalties_suppress_repeats():
    """A strong frequency penalty makes a repeated token's adjusted logit
    lose to the runner-up; counts drive the adjustment."""
    logits = jnp.zeros((1, 10)).at[0, 3].set(5.0).at[0, 7].set(4.0)
    st = _state(1, temperature=0.0, vocab_size=10)
    st = st._replace(frequency=jnp.asarray([0.6]))
    seen = []
    for _ in range(4):
        ids, st = sm.sample(logits, st)
        tok = int(ids[0])
        seen.append(tok)
        st = sm.count_tokens(st, ids)
    # Token 3 wins until its cumulative penalty (0.6/count) crosses the
    # 1.0 logit gap: 3, 3, then 7 takes over.
    assert seen[0] == 3 and seen[1] == 3
    assert 7 in seen[2:]


def test_penalties_are_identity_at_zero():
    logits = jax.random.normal(jax.random.PRNGKey(5), (3, 100))
    st = _state(3, temperature=0.0)
    st = sm.count_tokens(st, jnp.asarray([1, 2, 3]))  # counts but no penalty
    ids, _ = sm.sample(logits, st)
    assert np.array_equal(np.asarray(ids), np.asarray(jnp.argmax(logits, -1)))


def test_np_prng_key_matches_jax():
    """The host-side key constructor must be byte-identical to
    jax.random.PRNGKey — leader admissions and follower replay both use
    it, and a mismatch would silently diverge gang sampling."""
    import jax
    import numpy as np

    from arks_tpu.engine.sampler import np_prng_key

    for seed in (0, 1, 7, 2**31 - 1, 2**31, 2**63 - 1, -1, -2**31,
                 123456789):
        np.testing.assert_array_equal(
            np_prng_key(seed), np.asarray(jax.random.PRNGKey(seed)),
            err_msg=f"seed={seed}")


def test_logit_bias_forces_and_blocks_tokens():
    """OpenAI logit_bias: +100 forces a token, -100 (or -inf-ish) removes
    it — greedy and sampled alike, through the engine end to end."""
    from arks_tpu.engine import EngineConfig, InferenceEngine
    from arks_tpu.engine.tokenizer import ByteTokenizer
    from arks_tpu.engine.types import Request, SamplingParams
    from arks_tpu.models import get_config

    cfg = get_config("tiny")
    ecfg = EngineConfig(model="tiny", num_slots=2, max_cache_len=64,
                        prefill_buckets=(8, 16), steps_per_dispatch=4)
    eng = InferenceEngine(cfg, ecfg, ByteTokenizer())
    eng.start()
    try:
        def run(bias):
            r = Request(f"b{bias}", [5, 6, 7], SamplingParams(
                max_tokens=5, temperature=0.0, ignore_eos=True,
                logit_bias=bias))
            eng.add_request(r)
            ids = []
            while True:
                out = r.outputs.get(timeout=60)
                ids.extend(out.token_ids)
                if out.finished:
                    return ids

        base = run(())
        # +100 on an arbitrary token dominates every real logit (tiny
        # random models have |logits| << 100): the whole stream pins to it.
        forced = run(((123, 100.0),))
        assert forced == [123] * 5
        # -100 on the baseline's first token evicts it everywhere.
        banned = run(((base[0], -100.0),))
        assert base[0] not in banned
    finally:
        eng.stop()


def test_min_tokens_suppresses_stop_until_minimum():
    """min_tokens holds eos/stop ids out of the distribution until the
    minimum is generated: a stop id that greedy decoding would emit early
    cannot terminate the stream before min_tokens."""
    from arks_tpu.engine import EngineConfig, InferenceEngine
    from arks_tpu.engine.tokenizer import ByteTokenizer
    from arks_tpu.engine.types import Request, SamplingParams
    from arks_tpu.models import get_config

    cfg = get_config("tiny")
    ecfg = EngineConfig(model="tiny", num_slots=2, max_cache_len=64,
                        prefill_buckets=(8, 16), steps_per_dispatch=4)
    eng = InferenceEngine(cfg, ecfg, ByteTokenizer())
    eng.start()
    try:
        def run(params):
            r = Request(f"m{params.min_tokens}{params.stop_token_ids}",
                        [5, 6, 7], params)
            eng.add_request(r)
            ids = []
            while True:
                out = r.outputs.get(timeout=60)
                ids.extend(out.token_ids)
                if out.finished:
                    return ids, out

        base, _ = run(SamplingParams(max_tokens=8, temperature=0.0,
                                     ignore_eos=True))
        stop = base[1]  # greedy would emit this as token #2
        # Without min_tokens the stream stops right there.
        early, fin = run(SamplingParams(max_tokens=8, temperature=0.0,
                                        ignore_eos=True,
                                        stop_token_ids=(stop,)))
        assert fin.finish_reason == "stop" and len(early) <= 2
        # With min_tokens=5 the stop id is suppressed until 5 tokens exist.
        late, fin5 = run(SamplingParams(max_tokens=8, temperature=0.0,
                                        ignore_eos=True, min_tokens=5,
                                        stop_token_ids=(stop,)))
        assert len(late) >= 5
        assert stop not in late[:4]
    finally:
        eng.stop()
