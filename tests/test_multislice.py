"""Multi-slice mesh (DCN-modeled outermost 'slice' axis): construction,
batch-axis resolution, and numerical parity of decode/train across slices
vs a single-mesh oracle — on the 8-device virtual CPU mesh (conftest)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from arks_tpu.models import get_config
from arks_tpu.models import transformer as tf
from arks_tpu.parallel.mesh import make_mesh, make_multislice_mesh


def test_multislice_mesh_axes_and_validation():
    devs = jax.devices()[:8]
    mesh = make_multislice_mesh(2, tensor_parallel=2, data_parallel=2,
                                devices=devs)
    assert mesh.axis_names == ("slice", "data", "stage", "seq", "model")
    assert mesh.shape["slice"] == 2
    assert mesh.shape["data"] == 2
    assert mesh.shape["model"] == 2
    # The slice axis is outermost: devices 0-3 form slice 0 (process-major
    # order on real hardware = slice-local contiguity).
    assert list(mesh.devices[0].flatten()) == devs[:4]
    with pytest.raises(ValueError, match="num_slices"):
        make_multislice_mesh(3, devices=devs)


def test_batch_axis_for():
    devs = jax.devices()[:8]
    ms = make_multislice_mesh(2, tensor_parallel=2, data_parallel=2,
                              devices=devs)
    assert tf.batch_axis_for(ms) == ("slice", "data")
    ms2 = make_multislice_mesh(2, tensor_parallel=4, data_parallel=1,
                               devices=devs)
    assert tf.batch_axis_for(ms2) == "slice"
    flat = make_mesh(tensor_parallel=4, data_parallel=2, devices=devs)
    assert tf.batch_axis_for(flat) == "data"
    tponly = make_mesh(tensor_parallel=8, devices=devs)
    assert tf.batch_axis_for(tponly) is None
    assert tf.batch_axis_for(None) is None


def test_multislice_decode_matches_single_device():
    """Decode over (slice=2, data=2, model=2) == unsharded decode: the
    slice axis is a pure layout axis, never a math axis."""
    cfg = get_config("tiny-gqa")
    params = tf.init_params(cfg, jax.random.PRNGKey(0), jnp.float32)
    batch, max_len = 8, 32
    tokens = jax.random.randint(jax.random.PRNGKey(1), (batch,), 2, 200)
    lengths = jnp.full((batch,), 5, jnp.int32)

    cache0 = tf.init_cache(cfg, batch, max_len, jnp.float32)
    ref_logits, _ = tf.decode_step(params, cfg, cache0, tokens, lengths)

    mesh = make_multislice_mesh(2, tensor_parallel=2, data_parallel=2,
                                devices=jax.devices()[:8])
    ms_params = tf.shard_params(params, cfg, mesh)
    ms_cache = tf.shard_cache(tf.init_cache(cfg, batch, max_len,
                                            jnp.float32), cfg, mesh)
    decode = tf.make_decode_fn(cfg, mesh, batch_axis=tf.batch_axis_for(mesh))
    ms_logits, _ = decode(ms_params, ms_cache, tokens, lengths)
    np.testing.assert_allclose(np.asarray(ms_logits), np.asarray(ref_logits),
                               atol=2e-4, rtol=2e-4)


def test_multislice_train_step_matches_single_mesh():
    """One SGD step on the 2-slice mesh == the flat (data=4, model=2) mesh:
    the gradient all-reduce spanning the DCN axis must be numerically the
    same psum, just routed differently."""
    from arks_tpu.train.sft import make_train_step, train_init

    cfg = get_config("tiny-gqa")
    optimizer = optax.sgd(1e-2)
    devs = jax.devices()[:8]
    tokens = jax.random.randint(jax.random.PRNGKey(2), (8, 16), 2, 200)
    mask = jnp.ones((8, 16), jnp.float32)

    ms_mesh = make_multislice_mesh(2, tensor_parallel=2, data_parallel=2,
                                   devices=devs)
    flat_mesh = make_mesh(tensor_parallel=2, data_parallel=4, devices=devs)
    losses = []
    for mesh in (ms_mesh, flat_mesh):
        state = train_init(cfg, jax.random.PRNGKey(3), optimizer, mesh)
        step = make_train_step(cfg, optimizer, mesh)
        state, loss = step(state, tokens, tokens, mask)
        losses.append(float(loss))
    assert losses[0] == pytest.approx(losses[1], rel=1e-5)
