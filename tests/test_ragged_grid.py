"""Ragged work-list grid: engine-level stream identity vs the dense grid
(the pre-refactor kernel), padding-waste counters, and autotune config
surfacing.

The kernel-level bitwise identity between the two grids lives in
test_paged_attention.py; HERE the gate is the serving stream: the same
workload through ARKS_MIXED_GRID=ragged and =dense must emit byte-identical
token streams with the Pallas mixed path engaged (interpret mode on CPU),
at pipeline depths 0 and 2, for plain, guided, and speculative traffic.
"""

import numpy as np
import pytest

from arks_tpu.engine import EngineConfig, InferenceEngine, Request, SamplingParams
from arks_tpu.engine.tokenizer import ByteTokenizer
from arks_tpu.models import get_config


def _mk_engine(monkeypatch, *, grid, depth=0, impl="pallas", spec=False,
               **kw):
    monkeypatch.setenv("ARKS_MIXED_STEP", "1")
    monkeypatch.setenv("ARKS_MIXED_GRID", grid)
    monkeypatch.setenv("ARKS_ATTN_IMPL", impl)
    monkeypatch.setenv("ARKS_PIPELINE_DEPTH", str(depth))
    cfg = get_config("tiny")
    defaults = dict(model="tiny", num_slots=2, max_cache_len=64,
                    prefill_buckets=(8, 16, 32), steps_per_dispatch=4,
                    prefill_chunk=16, kv_layout="paged", prefix_cache_mb=0)
    if spec:
        defaults.update(draft_model="tiny", draft_len=3)
    defaults.update(kw)
    eng = InferenceEngine(cfg, EngineConfig(**defaults), ByteTokenizer())
    if depth:
        assert eng._pipe_warm_wait(300) == "ready"
    return cfg, eng


def _drive(eng, n_steps=2000):
    for _ in range(n_steps):
        eng.step(block_s=0.01)
        if (eng.num_running == 0 and eng._queue.empty()
                and not eng._prefilling):
            break


def _collect(req):
    ids, fin = [], None
    while True:
        out = req.outputs.get(timeout=120)
        ids.extend(out.token_ids)
        if out.finished:
            fin = out
            break
    return ids, fin.finish_reason


def _run_workload(eng, cfg, guided=False):
    """Plain greedy + fixed-seed sampled (+ optionally guided) requests —
    chunked and one-shot prompt shapes, more requests than slots."""
    reqs = [
        Request("g0", [5, 6, 7], SamplingParams(
            max_tokens=5, temperature=0.0, ignore_eos=True)),
        Request("s0", [int(x) % cfg.vocab_size for x in range(3, 40)],
                SamplingParams(max_tokens=5, temperature=0.8, top_p=0.9,
                               seed=7, ignore_eos=True)),
        Request("g1", [9] * 20, SamplingParams(
            max_tokens=5, temperature=0.0, ignore_eos=True)),
    ]
    if guided:
        reqs.append(Request("j0", [4, 8, 2], SamplingParams(
            max_tokens=6, temperature=0.0, guide=("json", ""))))
    for r in reqs:
        eng.add_request(r)
    _drive(eng)
    return [_collect(r) for r in reqs]


@pytest.mark.parametrize("depth", [0, 2])
def test_stream_identity_ragged_vs_dense(monkeypatch, depth):
    """Plain + guided traffic through the Pallas mixed path: the ragged
    grid's token streams are byte-identical to the dense grid's at this
    pipeline depth."""
    outs = {}
    for grid in ("ragged", "dense"):
        cfg, eng = _mk_engine(monkeypatch, grid=grid, depth=depth)
        assert eng.resolved_config["mixed_grid"] == grid
        outs[grid] = _run_workload(eng, cfg, guided=True)
    assert outs["ragged"] == outs["dense"]


@pytest.mark.parametrize("depth", [0, 2])
def test_stream_identity_spec_traffic(monkeypatch, depth):
    """Speculative traffic (draft+verify ride the mixed dispatch): ragged
    and dense grids emit identical accepted streams at this depth."""
    outs = {}
    for grid in ("ragged", "dense"):
        cfg, eng = _mk_engine(monkeypatch, grid=grid, depth=depth,
                              spec=True)
        outs[grid] = _run_workload(eng, cfg)
    assert outs["ragged"] == outs["dense"]


def test_sparse_batch_grid_steps_drop_to_ideal(monkeypatch):
    """3 active requests in a 64-slot engine: the ragged grid's executed
    page-compute steps equal the per-sequence causal ideal — and sit far
    below the dense grid's S*num_qb*max_pages.  Counters describe the grid
    PLAN, so this runs on the fast XLA oracle."""
    cfg, eng = _mk_engine(monkeypatch, grid="ragged", impl="xla",
                          num_slots=64)
    for i in range(3):
        eng.add_request(Request(f"r{i}", [5 + i, 6, 7], SamplingParams(
            max_tokens=4, temperature=0.0, ignore_eos=True)))
    _drive(eng)
    steps = eng.metrics.mixed_grid_steps_total.total()
    ideal = eng.metrics.mixed_grid_steps_ideal_total.total()
    assert steps == ideal > 0
    # The dense plan for the same dispatches: every issued dispatch pays
    # S * num_qb * max_pages.
    plan = next(iter(eng._grid_plans.values()))
    n_dispatches = sum(
        n for _, _, n in eng.metrics.mixed_batch_tokens._data.values())
    dense = 64 * plan["num_qb"] * eng._max_pages * n_dispatches
    assert steps < dense / 10, (steps, dense)


def test_gqa_bytes_sweep_hits_group_factor(monkeypatch):
    """The bench GQA sweep's acceptance shape: at g=8 the grouped tuned
    plan moves >= g fewer KV bytes than the ungrouped baseline (the win
    arrives through the larger tuned block_q that head grouping's VMEM
    headroom affords), and the grouped plan reaches the
    fetch-each-block-once ideal.  Plan-only — no kernel launches; the
    bitwise identity of the grouped kernel lives in
    test_paged_attention.py."""
    monkeypatch.delenv("ARKS_MIXED_GRID", raising=False)
    import bench
    r = bench.measure_gqa_bytes_sweep()
    assert r["gqa_g8_bytes_ratio"] >= 8
    assert r["gqa_g8_grouped_kv_bytes"] == r["gqa_g8_kv_bytes_ideal"]
    # The win scales with the GQA share factor.
    assert (r["gqa_g1_bytes_ratio"] < r["gqa_g4_bytes_ratio"]
            < r["gqa_g8_bytes_ratio"])


def test_kv_bytes_moved_counter_pair(monkeypatch):
    """Every mixed dispatch accounts the KV bytes its grid plan moves
    (mixed_kv_bytes_total) against the fetch-each-block-once ideal
    (mixed_kv_bytes_ideal_total) — the waste ratio the head-grouped DMA
    restructure is gated on.  Counters describe the PLAN, so the fast
    XLA oracle drives them; actual >= ideal always, and with the
    head-group factor covering every kv head in one pass the pair
    converges for single-page decode dispatches."""
    cfg, eng = _mk_engine(monkeypatch, grid="ragged", impl="xla",
                          num_slots=4)
    for i in range(2):
        eng.add_request(Request(f"r{i}", [5 + i, 6, 7], SamplingParams(
            max_tokens=4, temperature=0.0, ignore_eos=True)))
    _drive(eng)
    actual = eng.metrics.mixed_kv_bytes_total.total()
    ideal = eng.metrics.mixed_kv_bytes_ideal_total.total()
    assert ideal > 0
    assert actual >= ideal
    # The tiny model's decode batches fit one q-block, so the ragged
    # plan fetches each (seq, page) block exactly once: no waste.
    plan = next(iter(eng._grid_plans.values()))
    if plan["num_qb"] == 1:
        assert actual == ideal


def test_dense_grid_counts_padding_waste(monkeypatch):
    """Under ARKS_MIXED_GRID=dense the counter pair splits: steps_total
    records the dense grid's full S*num_qb*max_pages while ideal_total
    stays at the causal minimum — the waste ratio operators alert on."""
    cfg, eng = _mk_engine(monkeypatch, grid="dense", impl="xla",
                          num_slots=8)
    eng.add_request(Request("r0", [5, 6, 7], SamplingParams(
        max_tokens=3, temperature=0.0, ignore_eos=True)))
    _drive(eng)
    steps = eng.metrics.mixed_grid_steps_total.total()
    ideal = eng.metrics.mixed_grid_steps_ideal_total.total()
    assert ideal > 0 and steps > ideal
