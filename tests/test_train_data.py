"""Training data pipeline (train/data.py): packing math, SFT masking,
shard disjointness, determinism, prefetch, and an end-to-end train step."""

import json

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from arks_tpu.engine.tokenizer import ByteTokenizer
from arks_tpu.models import get_config
from arks_tpu.train.data import PackedDataset, prefetch, read_jsonl


def _records(n=40):
    return [{"text": f"document number {i} " + "x" * (i % 17)}
            for i in range(n)]


def test_packing_covers_stream_exactly():
    """Windows tile the EOS-joined token stream: tokens are contiguous,
    targets are tokens shifted by one, nothing repeats or is skipped
    until the dropped tail."""
    tok = ByteTokenizer()
    ds = PackedDataset(_records(), tok, seq_len=32, batch_size=2, seed=3)
    # Rebuild the reference stream in the SAME shuffled order.
    order = list(range(len(ds.records)))
    import random as _r
    _r.Random("3/0").shuffle(order)
    stream = []
    for i in order:
        stream.extend(tok.encode(ds.records[i]["text"]) + [0])

    flat_toks, flat_tgts = [], []
    for batch in ds.epoch(0):
        assert batch["tokens"].shape == (2, 32)
        assert batch["tokens"].dtype == np.int32
        assert batch["loss_mask"].dtype == np.float32
        flat_toks.extend(batch["tokens"].reshape(-1).tolist())
        flat_tgts.extend(batch["targets"].reshape(-1).tolist())
    n = len(flat_toks)
    assert n > 0 and n % 64 == 0
    # Window w starts at position w*T of the stream; its targets at +1.
    for w in range(n // 32):
        assert flat_toks[w * 32: (w + 1) * 32] == \
            stream[w * 32: w * 32 + 32]
        assert flat_tgts[w * 32: (w + 1) * 32] == \
            stream[w * 32 + 1: w * 32 + 33]


def test_sft_prompt_masking():
    """prompt/completion records train on completions (+EOS) only."""
    tok = ByteTokenizer()
    recs = [{"prompt": "Q: abc", "completion": " A: de"}] * 8
    ds = PackedDataset(recs, tok, seq_len=13, batch_size=1, seed=0)
    plen = len(tok.encode("Q: abc"))
    batch = next(iter(ds.epoch(0)))
    toks = batch["tokens"][0].tolist()
    mask = batch["loss_mask"][0].tolist()
    # Document length = 6 + 6 + 1(EOS) = 13 = seq_len, so window 0 holds
    # one document PLUS one lookahead target (the next doc's first prompt
    # token).  Target positions 0..plen-2 predict prompt tokens -> masked;
    # completion + EOS -> trained; the final cross-document target is the
    # next prompt's first token -> masked again.
    assert toks[:plen] == tok.encode("Q: abc")
    assert mask[: plen - 1] == [0.0] * (plen - 1)
    assert mask[plen - 1: -1] == [1.0] * (13 - plen)
    assert mask[-1] == 0.0  # next document's prompt token


def test_shards_are_disjoint_equal_and_cover():
    """Window-level sharding: disjoint stripes, EVERY shard yields the
    same batch count (unequal counts would deadlock the collective train
    step at the epoch tail), and the union covers the capped windows."""
    tok = ByteTokenizer()
    recs = _records(30)
    # The shard-independent window basis (what every process computes).
    full = PackedDataset(recs, tok, seq_len=16, batch_size=2, seed=1)
    windows = full._windows(0)
    per_shard = len(windows) // 3
    counts = []
    for s in range(3):
        ds = PackedDataset(recs, tok, seq_len=16, batch_size=2, seed=1,
                           shard_index=s, shard_count=3)
        batches = list(ds.epoch(0))
        counts.append(len(batches))
        assert len(batches) == ds.batches_per_epoch(0)
        # Shard s's rows are exactly stripe s of the shared basis —
        # disjoint BY POSITION (content can repeat in a repetitive
        # corpus) and in order.
        rows = [row.tolist() for b in batches for row in b["tokens"]]
        expect = [w[0] for w in windows[s::3][:per_shard]]
        assert rows == expect[: len(rows)]
    assert counts[0] > 0 and len(set(counts)) == 1  # equal batch counts
    with pytest.raises(ValueError, match="shard_index"):
        PackedDataset(recs, tok, 16, 1, shard_index=3, shard_count=3)


def test_prefetch_propagates_errors_and_releases_worker():
    """A crash mid-iterator re-raises in the consumer (not a silent short
    epoch), and abandoning the generator unblocks the worker thread."""
    import threading
    import time

    def boom():
        yield {"tokens": np.zeros((1, 4), np.int32)}
        raise RuntimeError("malformed record")

    it = prefetch(boom(), depth=2)
    next(it)
    with pytest.raises(RuntimeError, match="malformed record"):
        next(it)

    n_before = threading.active_count()
    many = prefetch(iter([{"i": i} for i in range(100)]), depth=1)
    next(many)
    many.close()  # abandon: cancel flag must release the blocked worker
    deadline = time.monotonic() + 5
    while time.monotonic() < deadline:
        if threading.active_count() <= n_before:
            break
        time.sleep(0.02)
    assert threading.active_count() <= n_before


def test_determinism_and_epoch_reshuffle():
    tok = ByteTokenizer()
    ds = PackedDataset(_records(), tok, seq_len=24, batch_size=2, seed=7)
    a = [b["tokens"] for b in ds.epoch(0)]
    b = [b["tokens"] for b in ds.epoch(0)]
    c = [b["tokens"] for b in ds.epoch(1)]
    assert all(np.array_equal(x, y) for x, y in zip(a, b))
    assert len(a) == len(b)
    assert any(not np.array_equal(x, y) for x, y in zip(a, c))


def test_read_jsonl_and_prefetch(tmp_path):
    path = tmp_path / "d.jsonl"
    path.write_text("\n".join(json.dumps(r) for r in _records(12)) + "\n")
    tok = ByteTokenizer()
    ds = PackedDataset(read_jsonl(str(path)), tok, seq_len=16,
                       batch_size=2, seed=0)
    direct = [b["tokens"] for b in ds.epoch(0)]
    fetched = [b["tokens"] for b in prefetch(ds.epoch(0), depth=2)]
    assert len(direct) == len(fetched) > 0
    assert all(np.array_equal(x, y) for x, y in zip(direct, fetched))


def test_feeds_train_step():
    """The pipeline's batches drive a real sharded train step (dp batch
    axis) and the loss goes down over a few epochs of a tiny corpus."""
    from arks_tpu.parallel.mesh import make_mesh
    from arks_tpu.train.sft import make_train_step, train_init

    cfg = get_config("tiny")
    tok = ByteTokenizer()
    mesh = make_mesh(tensor_parallel=2, data_parallel=2,
                     devices=jax.devices()[:4])
    optimizer = optax.adamw(3e-3)
    state = train_init(cfg, jax.random.PRNGKey(0), optimizer, mesh)
    step_fn = make_train_step(cfg, optimizer, mesh)
    ds = PackedDataset(_records(16), tok, seq_len=32, batch_size=4, seed=0)
    losses = []
    for epoch in range(6):
        for batch in prefetch(ds.epoch(epoch)):
            state, loss = step_fn(state, jnp.asarray(batch["tokens"]),
                                  jnp.asarray(batch["targets"]),
                                  jnp.asarray(batch["loss_mask"]))
            losses.append(float(loss))
    assert np.mean(losses[-3:]) < np.mean(losses[:3])


def test_trainer_cli_end_to_end_with_resume(tmp_path):
    """python -m arks_tpu.train: train N steps with checkpointing, then a
    SECOND invocation resumes from the latest step and reaches the target
    — the full training surface (data + sharded step + Orbax resume)
    through the real CLI."""
    import re
    import subprocess
    import sys

    data = tmp_path / "corpus.jsonl"
    data.write_text("\n".join(json.dumps(r) for r in _records(24)) + "\n")
    ckpt = tmp_path / "run"

    def run(steps):
        r = subprocess.run(
            [sys.executable, "-m", "arks_tpu.train", "--model", "tiny",
             "--data", str(data), "--seq-len", "32", "--batch-size", "4",
             "--steps", str(steps), "--lr", "3e-3",
             "--ckpt-dir", str(ckpt), "--ckpt-every", "5",
             "--log-every", "5", "--platform", "cpu"],
            capture_output=True, text=True, timeout=420)
        assert r.returncode == 0, r.stderr[-2000:]
        return r.stderr  # logging goes to stderr

    out1 = run(10)
    assert "step 10 loss" in out1
    assert "final checkpoint at step 10" in out1

    out2 = run(20)
    assert "resumed from step 10" in out2
    assert "final checkpoint at step 20" in out2
    # Loss kept improving across the restart boundary.
    losses = [float(m) for m in re.findall(r"loss (\d+\.\d+)", out1 + out2)]
    assert len(losses) >= 4 and losses[-1] < losses[0]


def test_trainer_cli_resume_fence_rejects_changed_shape(tmp_path):
    """Resuming with different data-shaping args must FAIL LOUDLY — a
    silently different stream would break the bit-identical replay."""
    import subprocess
    import sys

    data = tmp_path / "c.jsonl"
    data.write_text("\n".join(json.dumps(r) for r in _records(24)) + "\n")

    def run(extra):
        return subprocess.run(
            [sys.executable, "-m", "arks_tpu.train", "--model", "tiny",
             "--data", str(data), "--seq-len", "32", "--steps", "2",
             "--ckpt-dir", str(tmp_path / "run"), "--platform", "cpu",
             *extra],
            capture_output=True, text=True, timeout=420)

    assert run(["--batch-size", "4"]).returncode == 0
    r = run(["--batch-size", "8"])
    assert r.returncode != 0
    assert "different data-shaping args" in r.stderr
    assert "batch_size" in r.stderr
    # Original arguments still resume fine.
    assert run(["--batch-size", "4", "--steps", "4"]).returncode == 0
