"""Test env: force an 8-device virtual CPU mesh.

Mirrors the driver's multi-chip dry-run environment; all sharding tests run
against this mesh, never real TPU hardware.  Note: this image's sitecustomize
imports jax at interpreter startup (JAX_PLATFORMS=axon), so plain env vars are
too late here — switch the platform via jax.config before any backend is used.
"""

import os

# Pipelined decoding stays opt-in per test: at the production default
# (depth 2) every engine that reaches steady state kicks a background
# compile of both pipe-program variants, loading the CPU under the whole
# suite for no extra coverage — token streams are depth-invariant by
# contract, and tests/test_pipeline_decode.py asserts depths 1-3
# explicitly (its engines set this env themselves).
os.environ.setdefault("ARKS_PIPELINE_DEPTH", "0")

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_threefry_partitionable", True)
