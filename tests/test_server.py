"""HTTP surface tests: OpenAI wire contract incl. SSE streaming + usage."""

import json
import threading
import urllib.error
import urllib.request

import pytest

from arks_tpu.engine import EngineConfig, InferenceEngine
from arks_tpu.engine.tokenizer import ByteTokenizer
from arks_tpu.models import get_config
from arks_tpu.server import OpenAIServer


@pytest.fixture(scope="module")
def server():
    cfg = get_config("tiny")
    ecfg = EngineConfig(model="tiny", num_slots=2, max_cache_len=64,
                        prefill_buckets=(8, 16, 32), steps_per_dispatch=4)
    engine = InferenceEngine(cfg, ecfg, ByteTokenizer())
    engine.start()
    srv = OpenAIServer(engine, served_model_name="tiny-serve", host="127.0.0.1", port=0)
    srv.start(background=True)
    yield srv
    srv.stop()
    engine.stop()


def _post(server, path, body):
    req = urllib.request.Request(
        f"http://127.0.0.1:{server.port}{path}",
        data=json.dumps(body).encode(), headers={"Content-Type": "application/json"})
    return urllib.request.urlopen(req, timeout=120)


def test_models_list(server):
    with urllib.request.urlopen(f"http://127.0.0.1:{server.port}/v1/models") as r:
        data = json.load(r)
    assert data["object"] == "list"
    assert data["data"][0]["id"] == "tiny-serve"


def test_completion_non_stream(server):
    with _post(server, "/v1/completions", {
        "model": "tiny-serve", "prompt": "hi", "max_tokens": 6,
        "temperature": 0, "ignore_eos": True,
    }) as r:
        data = json.load(r)
    assert data["object"] == "text_completion"
    assert data["choices"][0]["finish_reason"] == "length"
    u = data["usage"]
    assert u["prompt_tokens"] == 2 and u["completion_tokens"] == 6
    assert u["total_tokens"] == 8


def test_chat_completion_non_stream(server):
    with _post(server, "/v1/chat/completions", {
        "model": "tiny-serve",
        "messages": [{"role": "user", "content": "hello"}],
        "max_tokens": 4, "temperature": 0, "ignore_eos": True,
    }) as r:
        data = json.load(r)
    assert data["object"] == "chat.completion"
    assert data["choices"][0]["message"]["role"] == "assistant"
    assert data["usage"]["completion_tokens"] == 4


def test_chat_stream_with_usage(server):
    frames = []
    with _post(server, "/v1/chat/completions", {
        "model": "tiny-serve",
        "messages": [{"role": "user", "content": "hello"}],
        "max_tokens": 5, "temperature": 0, "ignore_eos": True,
        "stream": True, "stream_options": {"include_usage": True},
    }) as r:
        assert r.headers["Content-Type"].startswith("text/event-stream")
        for raw in r:
            line = raw.decode().strip()
            if line.startswith("data: "):
                frames.append(line[len("data: "):])
    assert frames[-1] == "[DONE]"
    chunks = [json.loads(f) for f in frames[:-1]]
    assert chunks[0]["choices"][0]["delta"].get("role") == "assistant"
    finishes = [c["choices"][0]["finish_reason"] for c in chunks if c["choices"]]
    assert "length" in finishes
    usage_frames = [c for c in chunks if c.get("usage") is not None]
    assert len(usage_frames) == 1 and usage_frames[0]["choices"] == []
    assert usage_frames[0]["usage"]["completion_tokens"] == 5


def test_wrong_model_404(server):
    try:
        _post(server, "/v1/completions", {"model": "nope", "prompt": "x"})
        assert False, "expected HTTPError"
    except urllib.error.HTTPError as e:
        assert e.code == 404
        assert "not found" in json.load(e)["error"]["message"]


def test_bad_json_400(server):
    req = urllib.request.Request(
        f"http://127.0.0.1:{server.port}/v1/completions",
        data=b"{not json", headers={"Content-Type": "application/json"})
    try:
        urllib.request.urlopen(req, timeout=30)
        assert False
    except urllib.error.HTTPError as e:
        assert e.code == 400


def test_metrics_endpoint(server):
    with urllib.request.urlopen(f"http://127.0.0.1:{server.port}/metrics") as r:
        text = r.read().decode()
    assert "num_requests_running" in text
    assert "generation_tokens_total" in text




def test_stop_string_multi_token(server):
    # Learn greedy output first, then use a 2-char substring of it as stop.
    with _post(server, "/v1/completions", {
        "model": "tiny-serve", "prompt": "zq", "max_tokens": 8,
        "temperature": 0, "ignore_eos": True,
    }) as r:
        full = json.load(r)["choices"][0]["text"]
    assert len(full) >= 3
    stop = full[1:3]
    with _post(server, "/v1/completions", {
        "model": "tiny-serve", "prompt": "zq", "max_tokens": 8,
        "temperature": 0, "ignore_eos": True, "stop": [stop],
    }) as r:
        data = json.load(r)
    assert data["choices"][0]["finish_reason"] == "stop"
    assert stop not in data["choices"][0]["text"]
    assert data["choices"][0]["text"] == full[: full.find(stop)]


def test_engine_abort_frees_slot():
    from arks_tpu.engine import EngineConfig, InferenceEngine
    from arks_tpu.engine.types import Request, SamplingParams
    from arks_tpu.engine.tokenizer import ByteTokenizer
    from arks_tpu.models import get_config
    ecfg = EngineConfig(model="tiny", num_slots=1, max_cache_len=64,
                        prefill_buckets=(8,), steps_per_dispatch=2)
    eng = InferenceEngine(get_config("tiny"), ecfg, ByteTokenizer())
    req = Request("abort-me", [3, 4], SamplingParams(max_tokens=10_000, temperature=0.0,
                                                     ignore_eos=True))
    eng.add_request(req)
    eng.step(block_s=0.01)  # admit + first dispatch
    assert eng.num_running == 1
    eng.abort("abort-me")
    eng.step(block_s=0.01)  # abort consumed at the dispatch boundary
    assert eng.num_running == 0
    fin = None
    while True:
        out = req.outputs.get(timeout=30)
        if out.finished:
            fin = out
            break
    assert fin.finish_reason == "abort"


def test_small_max_model_len_no_crash():
    # Regression: max_cache_len below the smallest bucket must still admit.
    from arks_tpu.engine import EngineConfig, InferenceEngine, Request, SamplingParams
    from arks_tpu.engine.tokenizer import ByteTokenizer
    from arks_tpu.models import get_config
    ecfg = EngineConfig(model="tiny", num_slots=1, max_cache_len=20,
                        prefill_buckets=(32, 64), steps_per_dispatch=2)
    eng = InferenceEngine(get_config("tiny"), ecfg, ByteTokenizer())
    req = Request("tiny-cache", [1, 2, 3], SamplingParams(max_tokens=4, temperature=0.0,
                                                          ignore_eos=True))
    eng.add_request(req)
    for _ in range(50):
        eng.step(block_s=0.01)
        if eng.num_running == 0 and eng._queue.empty():
            break
    outs = []
    while True:
        out = req.outputs.get(timeout=30)
        outs.append(out)
        if out.finished:
            break
    assert outs[-1].finished

def test_batched_prompt_multi_choice(server):
    with _post(server, "/v1/completions", {
        "model": "tiny-serve", "prompt": ["ab", "cd"], "max_tokens": 3,
        "temperature": 0, "ignore_eos": True,
    }) as r:
        data = json.load(r)
    assert [c["index"] for c in data["choices"]] == [0, 1]
    assert all(c["finish_reason"] == "length" for c in data["choices"])
    assert data["usage"]["prompt_tokens"] == 4
    assert data["usage"]["completion_tokens"] == 6


def test_context_length_exceeded_400(server):
    """Oversize prompts get HTTP 400 with code context_length_exceeded
    (OpenAI semantics) — never silent truncation.  The tiny server's usable
    window is 64 - 4 - 1 = 59 tokens (ByteTokenizer: 1 byte = 1 token)."""
    with pytest.raises(urllib.error.HTTPError) as ei:
        _post(server, "/v1/completions", {
            "model": "tiny-serve", "prompt": "x" * 80, "max_tokens": 2,
        })
    assert ei.value.code == 400
    err = json.load(ei.value)["error"]
    assert err["code"] == "context_length_exceeded"
    assert "80" in err["message"]

    # Streaming path rejects the same way (before any SSE frame).
    with pytest.raises(urllib.error.HTTPError) as ei2:
        _post(server, "/v1/chat/completions", {
            "model": "tiny-serve", "stream": True,
            "messages": [{"role": "user", "content": "y" * 200}],
        })
    assert ei2.value.code == 400
    assert json.load(ei2.value)["error"]["code"] == "context_length_exceeded"


def test_long_prompt_chunked_through_server(server):
    """A prompt beyond the one-shot buckets (32) but inside the window (59)
    serves fine via chunked prefill."""
    with _post(server, "/v1/completions", {
        "model": "tiny-serve", "prompt": "z" * 50, "max_tokens": 3,
        "temperature": 0, "ignore_eos": True,
    }) as r:
        data = json.load(r)
    assert data["choices"][0]["finish_reason"] == "length"
    assert data["usage"]["prompt_tokens"] == 50


def test_empty_prompt_400(server):
    try:
        _post(server, "/v1/completions", {"model": "tiny-serve", "prompt": ""})
        assert False
    except urllib.error.HTTPError as e:
        assert e.code == 400


def test_stream_batch_prompt_400(server):
    try:
        _post(server, "/v1/completions", {
            "model": "tiny-serve", "prompt": ["a", "b"], "stream": True})
        assert False
    except urllib.error.HTTPError as e:
        assert e.code == 400


def _run_drain_scenario(extra_env=None):
    """Shared SIGTERM-drain scenario: start a serving subprocess, stream a
    long request, SIGTERM mid-stream, assert readiness/admission 503
    during the drain, the in-flight stream finishes to its LAST byte, and
    the process exits 0.  ``extra_env`` overrides engine env knobs (the
    pipelined-decode variant rides this)."""
    import json as _json
    import os
    import signal
    import subprocess
    import sys
    import threading
    import time as _time
    import urllib.error
    import urllib.request

    import socket as _socket
    s = _socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    env = dict(os.environ)
    env.update(extra_env or {})
    proc = subprocess.Popen(
        [sys.executable, "-m", "arks_tpu.server",
         "--model", "tiny", "--port", str(port), "--platform", "cpu",
         "--num-slots", "2", "--max-model-len", "64",
         "--steps-per-dispatch", "1", "--drain-timeout", "30"],
        stdout=subprocess.DEVNULL, stderr=subprocess.STDOUT, env=env)
    base = f"http://127.0.0.1:{port}"
    try:
        for _ in range(120):
            try:
                urllib.request.urlopen(base + "/readiness", timeout=2)
                break
            except Exception:
                _time.sleep(1)

        # Long streamed request (40 tokens at 1 step/dispatch: plenty of
        # wall time to SIGTERM in the middle).
        frames: list[str] = []
        err: list[Exception] = []

        def stream():
            req = urllib.request.Request(
                base + "/v1/completions",
                data=_json.dumps({"model": "tiny", "prompt": "drain me",
                                  "max_tokens": 40, "temperature": 0,
                                  "stream": True}).encode(),
                headers={"Content-Type": "application/json"})
            try:
                with urllib.request.urlopen(req, timeout=120) as r:
                    for raw in r:
                        line = raw.decode().strip()
                        if line.startswith("data: "):
                            frames.append(line[6:])
            except Exception as e:  # noqa: BLE001 — recorded for the assert
                err.append(e)

        t = threading.Thread(target=stream)
        t.start()
        # Wait until tokens are flowing, then SIGTERM.
        deadline = _time.monotonic() + 60
        while not frames and _time.monotonic() < deadline:
            _time.sleep(0.1)
        assert frames, "stream never started"
        os.kill(proc.pid, signal.SIGTERM)

        # While draining: readiness 503 and new completions 503.
        _time.sleep(0.5)
        try:
            urllib.request.urlopen(base + "/readiness", timeout=5)
            raise AssertionError("readiness should be 503 while draining")
        except urllib.error.HTTPError as e:
            assert e.code == 503
        try:
            urllib.request.urlopen(urllib.request.Request(
                base + "/v1/completions",
                data=_json.dumps({"model": "tiny", "prompt": "new",
                                  "max_tokens": 2}).encode(),
                headers={"Content-Type": "application/json"}), timeout=10)
            raise AssertionError("new work should be 503 while draining")
        except urllib.error.HTTPError as e:
            assert e.code == 503

        # The in-flight stream finishes COMPLETELY (to its last byte: the
        # finish frame carries finish_reason) and the process exits 0.
        t.join(timeout=120)
        assert not err, f"in-flight stream died during drain: {err}"
        assert frames[-1] == "[DONE]"
        payloads = [_json.loads(f) for f in frames[:-1]]
        text = "".join(c["text"] for p in payloads
                       for c in p.get("choices", []) if "text" in c)
        assert len(text) > 0
        finishes = [c["finish_reason"] for p in payloads
                    for c in p.get("choices", []) if c.get("finish_reason")]
        assert finishes == ["length"], finishes
        assert proc.wait(timeout=60) == 0
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=10)


def test_sigterm_drains_in_flight_requests():
    """Graceful drain: SIGTERM mid-request flips readiness to 503, rejects
    NEW completions, lets the in-flight streamed request finish, and the
    process exits cleanly — what makes rolling updates request-lossless."""
    _run_drain_scenario()


def test_sigterm_drains_under_pipelined_decode():
    """The same drain contract with ARKS_PIPELINE_DEPTH=2: SIGTERM with
    pipelined dispatches in flight must flip readiness, resolve/drain the
    in-flight pipeline, finish every live stream to its last byte, and
    exit within --drain-timeout.  (The conftest pins depth 0 for the
    suite; this subprocess re-enables the production default.)"""
    _run_drain_scenario({"ARKS_PIPELINE_DEPTH": "2"})


def test_logprobs_completions_and_chat(server):
    """OpenAI logprobs: completions int form and chat logprobs/top_logprobs
    form, with chosen-token logprobs matching a real log-softmax (negative,
    and for greedy the chosen token is the max of its top list)."""
    import math

    with _post(server, "/v1/completions",
               {"model": "tiny-serve", "prompt": "hello", "max_tokens": 6,
                "temperature": 0, "ignore_eos": True, "logprobs": 3}) as r:
        out = json.load(r)
    lp = out["choices"][0]["logprobs"]
    assert len(lp["tokens"]) == 6
    assert len(lp["token_logprobs"]) == 6
    assert all(v <= 0 for v in lp["token_logprobs"])
    # Dict keyed by token TEXT (the legacy format): distinct ids that
    # render identically (byte-tokenizer replacement chars) collapse.
    assert all(1 <= len(d) <= 3 for d in lp["top_logprobs"])
    for tok_lp, top in zip(lp["token_logprobs"], lp["top_logprobs"]):
        # Greedy: the chosen token is the global argmax, so its logprob
        # bounds every listed alternative (text-key collisions can hide
        # the chosen entry itself from the dict).
        assert tok_lp >= max(top.values()) - 1e-5
    assert lp["text_offset"][0] == 0
    assert lp["text_offset"] == sorted(lp["text_offset"])

    with _post(server, "/v1/chat/completions",
               {"model": "tiny-serve", "max_tokens": 4, "temperature": 0,
                "ignore_eos": True, "logprobs": True, "top_logprobs": 2,
                "messages": [{"role": "user", "content": "hi"}]}) as r:
        out = json.load(r)
    content = out["choices"][0]["logprobs"]["content"]
    assert len(content) == 4
    for e in content:
        assert e["logprob"] <= 0
        assert isinstance(e["bytes"], list)
        assert len(e["top_logprobs"]) == 2

    with _post(server, "/v1/completions",
               {"model": "tiny-serve", "prompt": "x", "max_tokens": 2,
                "temperature": 0, "ignore_eos": True}) as r:
        out = json.load(r)
    assert "logprobs" not in out["choices"][0]


def test_logprobs_streaming(server):
    entries = []
    with _post(server, "/v1/chat/completions",
               {"model": "tiny-serve", "max_tokens": 6, "temperature": 0,
                "ignore_eos": True, "logprobs": True, "top_logprobs": 1,
                "stream": True,
                "messages": [{"role": "user", "content": "go"}]}) as r:
        for raw in r:
            line = raw.decode().strip()
            if not line.startswith("data: ") or line == "data: [DONE]":
                continue
            for c in json.loads(line[6:]).get("choices", []):
                lp = c.get("logprobs")
                if lp:
                    entries.extend(lp["content"])
    assert len(entries) == 6  # one per generated token, across chunks
    assert all(e["logprob"] <= 0 for e in entries)


def test_logprobs_zero_means_chosen_only(server):
    """completions logprobs=0 and chat top_logprobs=0: logprob data present,
    alternatives lists empty (distinct from 'off')."""
    with _post(server, "/v1/completions",
               {"model": "tiny-serve", "prompt": "z", "max_tokens": 3,
                "temperature": 0, "ignore_eos": True, "logprobs": 0}) as r:
        out = json.load(r)
    lp = out["choices"][0]["logprobs"]
    assert len(lp["token_logprobs"]) == 3
    assert all(d == {} for d in lp["top_logprobs"])

    with _post(server, "/v1/chat/completions",
               {"model": "tiny-serve", "max_tokens": 3, "temperature": 0,
                "ignore_eos": True, "logprobs": True, "top_logprobs": 0,
                "messages": [{"role": "user", "content": "q"}]}) as r:
        out = json.load(r)
    content = out["choices"][0]["logprobs"]["content"]
    assert len(content) == 3
    assert all(e["top_logprobs"] == [] for e in content)


def test_logprobs_streaming_stop_cut_parity(server):
    """On a streamed stop-string cut, logprob entries for visible tokens
    still flush (only past-the-cut entries drop) — entry count and text
    match the non-stream path for the same request."""
    with _post(server, "/v1/completions", {
        "model": "tiny-serve", "prompt": "zq", "max_tokens": 8,
        "temperature": 0, "ignore_eos": True,
    }) as r:
        full = json.load(r)["choices"][0]["text"]
    assert len(full) >= 5
    # full[3:5] straddles the 4-token dispatch boundary: the first frame is
    # emitted (with its hold-back) before the cut is even detectable —
    # entries in the hold-back tail must NOT flush early.
    for stop in (full[1:3], full[3:5]):
        body = {"model": "tiny-serve", "prompt": "zq", "max_tokens": 8,
                "temperature": 0, "ignore_eos": True, "stop": [stop],
                "logprobs": 1}
        with _post(server, "/v1/completions", body) as r:
            ref = json.load(r)["choices"][0]
        assert ref["finish_reason"] == "stop"

        text, n_entries = "", 0
        with _post(server, "/v1/completions", dict(body, stream=True)) as r:
            for raw in r:
                line = raw.decode().strip()
                if not line.startswith("data: ") or line == "data: [DONE]":
                    continue
                for c in json.loads(line[6:]).get("choices", []):
                    text += c.get("text") or ""
                    lp = c.get("logprobs")
                    if lp:
                        n_entries += len(lp["tokens"])
        assert text == ref["text"]
        assert n_entries == len(ref["logprobs"]["tokens"])
        # The cut kept the visible-prefix tokens and dropped the rest.
        assert 0 < n_entries < 8


def test_logit_bias_and_min_tokens_api(server):
    """OpenAI logit_bias flows through the HTTP surface (+100 forces a
    token id across the stream) and oversized bias objects 400 instead
    of silently truncating; min_tokens passes through."""
    with _post(server, "/v1/completions", {
        "model": "tiny-serve", "prompt": "hi", "max_tokens": 4,
        "temperature": 0, "ignore_eos": True,
        "logit_bias": {"123": 100},
    }) as r:
        data = json.load(r)
    from arks_tpu.engine.tokenizer import ByteTokenizer
    assert data["choices"][0]["text"] == ByteTokenizer().decode([123] * 4)

    with _post(server, "/v1/completions", {
        "model": "tiny-serve", "prompt": "hi", "max_tokens": 4,
        "temperature": 0, "ignore_eos": True, "min_tokens": 3,
    }) as r:
        assert json.load(r)["usage"]["completion_tokens"] == 4

    from arks_tpu.engine.sampler import LOGIT_BIAS_MAX
    too_many = {str(i): 1 for i in range(LOGIT_BIAS_MAX + 1)}
    try:
        _post(server, "/v1/completions", {
            "model": "tiny-serve", "prompt": "hi", "max_tokens": 2,
            "logit_bias": too_many,
        })
        raise AssertionError("expected HTTP 400")
    except urllib.error.HTTPError as e:
        assert e.code == 400


def test_min_tokens_defers_stop_strings(server):
    """vLLM semantics: stop strings do not terminate or cut the stream
    until min_tokens completion tokens exist; text generated before the
    minimum is exempt from matching (the min-th token itself can stop)."""
    from arks_tpu.engine.tokenizer import ByteTokenizer
    ch = ByteTokenizer().decode([123])
    # Two-char stop -> multi-token, so it is matched server-side as a
    # string (single-token stops become device stop ids instead).
    body = {
        "model": "tiny-serve", "prompt": "hi", "max_tokens": 8,
        "temperature": 0, "ignore_eos": True, "min_tokens": 4,
        "logit_bias": {"123": 100}, "stop": [ch * 2],
    }
    with _post(server, "/v1/completions", body) as r:
        data = json.load(r)
    # Tokens 1-3 are exempt; the stop spanning tokens 3-4 matches (the
    # min-th token may complete a stop) and cuts at position 2.
    assert data["choices"][0]["finish_reason"] == "stop"
    assert data["choices"][0]["text"] == ch * 2

    frames = []
    with _post(server, "/v1/completions", {**body, "stream": True}) as r:
        for raw in r:
            line = raw.decode().strip()
            if line.startswith("data: "):
                frames.append(line[len("data: "):])
    chunks = [json.loads(f) for f in frames[:-1]]
    text = "".join(c["choices"][0]["text"] for c in chunks if c["choices"])
    finishes = [c["choices"][0]["finish_reason"] for c in chunks if c["choices"]]
    assert text == ch * 2
    assert "stop" in finishes


def test_guided_decoding_api(server):
    """Guided decoding over HTTP: guided_regex forces an exact JSON shape;
    response_format json_object keeps the stream inside the JSON grammar;
    invalid patterns 400."""
    with _post(server, "/v1/completions", {
        "model": "tiny-serve", "prompt": "hi", "max_tokens": 32,
        "temperature": 0, "guided_regex": '\\{"ok": (true|false)\\}',
    }) as r:
        data = json.load(r)
    assert data["choices"][0]["finish_reason"] == "stop"
    assert json.loads(data["choices"][0]["text"])["ok"] in (True, False)

    with _post(server, "/v1/chat/completions", {
        "model": "tiny-serve",
        "messages": [{"role": "user", "content": "produce json"}],
        "max_tokens": 12, "temperature": 0,
        "response_format": {"type": "json_object"},
    }) as r:
        data = json.load(r)
    text = data["choices"][0]["message"]["content"]
    from arks_tpu.engine.guides import compile_regex_dfa, json_mode_regex
    t, _ = compile_regex_dfa(json_mode_regex(3))
    st = 0
    for b in text.encode():
        st = t[st, b]
        assert st >= 0, f"dead JSON transition in {text!r}"

    # json_schema structured output.  eos (id 0) biased +100: the random
    # test model then ends at the FIRST grammar-legal point (the guide
    # masks eos everywhere before the object closes; the grammar's
    # trailing-whitespace star would otherwise let greedy wander to
    # max_tokens).
    with _post(server, "/v1/completions", {
        "model": "tiny-serve", "prompt": "s", "max_tokens": 48,
        "temperature": 0, "logit_bias": {"0": 100},
        "response_format": {"type": "json_schema", "json_schema": {
            "name": "t", "schema": {"type": "object", "properties": {
                "ok": {"type": "boolean"}}}}},
    }) as r:
        data = json.load(r)
    assert data["choices"][0]["finish_reason"] == "stop"
    assert json.loads(data["choices"][0]["text"])["ok"] in (True, False)

    try:
        _post(server, "/v1/completions", {
            "model": "tiny-serve", "prompt": "x", "max_tokens": 4,
            "guided_regex": "(unclosed"})
        raise AssertionError("expected HTTP 400")
    except urllib.error.HTTPError as e:
        assert e.code == 400


def test_guided_choice_api(server):
    """vLLM-style guided_choice round-trip: the completion is EXACTLY one
    of the literal choices (regex metacharacters escaped); non-string
    entries and empty lists 400."""
    choices = ["red", "green", "blu.e(x)"]  # metachars must be literal
    with _post(server, "/v1/completions", {
        "model": "tiny-serve", "prompt": "pick", "max_tokens": 16,
        "temperature": 0, "guided_choice": choices,
    }) as r:
        data = json.load(r)
    assert data["choices"][0]["finish_reason"] == "stop"
    assert data["choices"][0]["text"] in choices

    # Chat surface takes the extra too.
    with _post(server, "/v1/chat/completions", {
        "model": "tiny-serve",
        "messages": [{"role": "user", "content": "pick"}],
        "max_tokens": 16, "temperature": 0,
        "guided_choice": ["alpha", "beta"],
    }) as r:
        data = json.load(r)
    assert data["choices"][0]["message"]["content"] in ("alpha", "beta")

    for bad in (["ok", 3], [], "red", [None]):
        try:
            _post(server, "/v1/completions", {
                "model": "tiny-serve", "prompt": "x", "max_tokens": 4,
                "guided_choice": bad})
            raise AssertionError(f"expected HTTP 400 for {bad!r}")
        except urllib.error.HTTPError as e:
            assert e.code == 400


def test_find_stop_min_end_exemption():
    """A stop match ending at or before min_end is exempt, regardless of
    OTHER (longer) stop strings in the set; a straddling match cuts."""
    from arks_tpu.server.openai_server import _find_stop
    # "ab" lies wholly inside the exempt region: a longer stop in the set
    # must not widen the window and resurrect it.
    assert _find_stop("xxabyy", ["ab", "xxxxx"], min_end=4) is None
    # Straddle: the match's end crosses the boundary.
    assert _find_stop("xxabyy", ["ab"], min_end=3) == 2
    # A later, non-exempt occurrence is still found.
    assert _find_stop("abzzab", ["ab"], min_end=4) == 4
    # min_end=0 keeps the plain earliest-match behavior.
    assert _find_stop("zab", ["ab"], min_end=0) == 1


def test_engine_rejects_oversized_suppress_set():
    """add_request validates the min_tokens suppress budget on the CALLER's
    thread; overflowing inside the scheduler would abort every in-flight
    request (engine._run's blanket fault handler)."""
    from arks_tpu.engine.sampler import SUPPRESS_MAX, np_suppress_col
    from arks_tpu.engine.types import Request, SamplingParams
    cfg = get_config("tiny")
    ecfg = EngineConfig(model="tiny", num_slots=2, max_cache_len=64,
                        prefill_buckets=(8,), steps_per_dispatch=2)
    engine = InferenceEngine(cfg, ecfg, ByteTokenizer())
    params = SamplingParams(
        max_tokens=4, min_tokens=2, ignore_eos=True,
        stop_token_ids=tuple(range(SUPPRESS_MAX + 1)))
    req = Request(request_id="over", prompt_ids=[1, 2], params=params)
    with pytest.raises(ValueError, match="suppress set"):
        engine.add_request(req)
    with pytest.raises(ValueError, match="suppress set"):
        np_suppress_col(range(SUPPRESS_MAX + 1))


def test_n_choices(server):
    """OpenAI n: one independent sample per choice.  Greedy choices are
    identical; seeded sampled choices differ (child seeds seed+j) while
    the whole request stays reproducible."""
    with _post(server, "/v1/completions", {
        "model": "tiny-serve", "prompt": "hi", "max_tokens": 4,
        "temperature": 0, "ignore_eos": True, "n": 3,
    }) as r:
        data = json.load(r)
    texts = [c["text"] for c in data["choices"]]
    assert len(texts) == 3 and len(set(texts)) == 1  # greedy: identical
    assert [c["index"] for c in data["choices"]] == [0, 1, 2]
    assert data["usage"]["completion_tokens"] == 12

    def sampled():
        with _post(server, "/v1/completions", {
            "model": "tiny-serve", "prompt": "hi", "max_tokens": 6,
            "temperature": 1.0, "seed": 11, "ignore_eos": True, "n": 3,
        }) as r:
            return [c["text"] for c in json.load(r)["choices"]]

    a = sampled()
    assert len(set(a)) > 1          # distinct child seeds -> diverse
    assert a == sampled()           # but reproducible end to end

    # Chat n: message choices.
    with _post(server, "/v1/chat/completions", {
        "model": "tiny-serve",
        "messages": [{"role": "user", "content": "hello"}],
        "max_tokens": 3, "temperature": 0, "ignore_eos": True, "n": 2,
    }) as r:
        data = json.load(r)
    assert data["object"] == "chat.completion"
    assert [c["message"]["role"] for c in data["choices"]] == ["assistant"] * 2

    # Streaming with n > 1 is rejected, not silently single-choice.
    try:
        _post(server, "/v1/completions", {
            "model": "tiny-serve", "prompt": "hi", "max_tokens": 2,
            "stream": True, "n": 2,
        })
        raise AssertionError("expected HTTP 400")
    except urllib.error.HTTPError as e:
        assert e.code == 400


def test_echo_parameter(server):
    """Completions echo=true prepends the prompt text to the choice
    (non-stream only; chat and streaming reject it)."""
    with _post(server, "/v1/completions", {
        "model": "tiny-serve", "prompt": "hi", "max_tokens": 3,
        "temperature": 0, "ignore_eos": True, "echo": True,
    }) as r:
        data = json.load(r)
    text = data["choices"][0]["text"]
    assert text.startswith("hi") and len(text) > 2
    assert data["usage"]["completion_tokens"] == 3

    # echo + logprobs: text_offset starts past the echoed prompt, so
    # clients slicing choice.text by offset get the right substrings.
    with _post(server, "/v1/completions", {
        "model": "tiny-serve", "prompt": "hi", "max_tokens": 3,
        "temperature": 0, "ignore_eos": True, "echo": True, "logprobs": 0,
    }) as r:
        lp = json.load(r)["choices"][0]["logprobs"]
    assert lp["text_offset"][0] == len("hi")

    for bad in ({"stream": True}, {"_chat_probe": True}):
        body = {"model": "tiny-serve", "prompt": "hi", "max_tokens": 2,
                "echo": True, **bad}
        path = "/v1/completions"
        if bad.get("_chat_probe"):
            body = {"model": "tiny-serve", "max_tokens": 2, "echo": True,
                    "messages": [{"role": "user", "content": "x"}]}
            path = "/v1/chat/completions"
        try:
            _post(server, path, body)
            raise AssertionError("expected HTTP 400")
        except urllib.error.HTTPError as e:
            assert e.code == 400


def test_tier_header_maps_to_priority(server):
    """x-arks-tier -> params.priority (arks_tpu.slo): the header wins
    over a body "priority", and an unknown tier 400s even direct-to-pod
    (the gateway normally validates first, but must not be the only
    line)."""
    from arks_tpu import slo as slo_mod
    old = server.slo
    server.slo = slo_mod.parse_tiers("latency:ttft_ms=300,batch:")
    try:
        seen = []
        orig = server.engine.add_request

        def spy(req):
            seen.append(req.params.priority)
            return orig(req)

        server.engine.add_request = spy
        try:
            req = urllib.request.Request(
                f"http://127.0.0.1:{server.port}/v1/completions",
                data=json.dumps({"model": "tiny-serve", "prompt": "hi",
                                 "max_tokens": 2, "ignore_eos": True,
                                 "priority": 0}).encode(),
                headers={"Content-Type": "application/json",
                         "x-arks-tier": "batch"})
            with urllib.request.urlopen(req, timeout=120) as r:
                assert r.status == 200
            assert seen == [1], seen  # batch = index 1, beats body 0
        finally:
            server.engine.add_request = orig
        req = urllib.request.Request(
            f"http://127.0.0.1:{server.port}/v1/completions",
            data=json.dumps({"model": "tiny-serve", "prompt": "hi",
                             "max_tokens": 2}).encode(),
            headers={"Content-Type": "application/json",
                     "x-arks-tier": "bogus"})
        try:
            urllib.request.urlopen(req, timeout=30)
            raise AssertionError("expected 400")
        except urllib.error.HTTPError as e:
            assert e.code == 400
            assert "bogus" in json.load(e)["error"]["message"]
    finally:
        server.slo = old


def test_tenant_header_maps_to_request(server):
    """x-arks-tenant (gateway-minted, router-forwarded) lands on
    Request.tenant — the engine's fair-queue key."""
    seen = []
    orig = server.engine.add_request

    def spy(req):
        seen.append(req.tenant)
        return orig(req)

    server.engine.add_request = spy
    try:
        req = urllib.request.Request(
            f"http://127.0.0.1:{server.port}/v1/completions",
            data=json.dumps({"model": "tiny-serve", "prompt": "hi",
                             "max_tokens": 2, "ignore_eos": True}).encode(),
            headers={"Content-Type": "application/json",
                     "x-arks-tenant": "acme/alice"})
        with urllib.request.urlopen(req, timeout=120) as r:
            assert r.status == 200
        # No header -> None (untenanted single lane).
        with _post(server, "/v1/completions",
                   {"model": "tiny-serve", "prompt": "hi",
                    "max_tokens": 2, "ignore_eos": True}) as r:
            assert r.status == 200
    finally:
        server.engine.add_request = orig
    assert seen == ["acme/alice", None], seen


def test_queue_full_maps_to_http(server):
    """Bounded-queue rejections map by scope: the global cap is a
    saturated backend (503 queue_full), a per-tenant cap is the caller's
    own backlog (429 tenant_queue_full) — both with Retry-After and the
    saturation header."""
    from arks_tpu.engine import fairqueue
    orig = server.engine.add_request

    def reject_tenant(req):
        raise fairqueue.QueueFullError("tenant", "acme/alice", 8, 8, 3)

    def reject_queue(req):
        raise fairqueue.QueueFullError("queue", "acme/alice", 64, 64, 7)

    try:
        server.engine.add_request = reject_tenant
        try:
            _post(server, "/v1/completions",
                  {"model": "tiny-serve", "prompt": "hi", "max_tokens": 2})
            raise AssertionError("expected HTTP 429")
        except urllib.error.HTTPError as e:
            assert e.code == 429
            assert e.headers["Retry-After"] == "3"
            assert e.headers["x-arks-tenant"] == "acme/alice"
            assert e.headers["x-arks-saturation"] is not None
            assert json.load(e)["error"]["code"] == "tenant_queue_full"
        server.engine.add_request = reject_queue
        try:
            _post(server, "/v1/completions",
                  {"model": "tiny-serve", "prompt": "hi", "max_tokens": 2})
            raise AssertionError("expected HTTP 503")
        except urllib.error.HTTPError as e:
            assert e.code == 503
            assert e.headers["Retry-After"] == "7"
            assert json.load(e)["error"]["code"] == "queue_full"
    finally:
        server.engine.add_request = orig


def test_shed_deadline_maps_to_503_with_retry_after(server):
    """A deadline-shed engine output (queued past the tier's TTFT
    budget) is capacity, not client error: 503 + drain-derived
    Retry-After, code shed_deadline."""
    from arks_tpu.engine.types import RequestOutput
    orig = server.engine.add_request

    def shed(req):
        req.outputs.put(RequestOutput(
            request_id=req.request_id, token_ids=[], finished=True,
            finish_reason="error",
            error="shed_deadline: queued 9.00s, tier 1 ttft budget "
                  "already unmeetable", num_prompt_tokens=2))

    server.engine.add_request = shed
    try:
        try:
            _post(server, "/v1/completions",
                  {"model": "tiny-serve", "prompt": "hi", "max_tokens": 2})
            raise AssertionError("expected HTTP 503")
        except urllib.error.HTTPError as e:
            assert e.code == 503
            assert int(e.headers["Retry-After"]) >= 1
            assert json.load(e)["error"]["code"] == "shed_deadline"
    finally:
        server.engine.add_request = orig


def test_readiness_exports_admission_saturation(server):
    """/readiness carries the queue-saturation block so edges can back
    off BEFORE the bounded queue starts rejecting."""
    with urllib.request.urlopen(
            f"http://127.0.0.1:{server.port}/readiness", timeout=30) as r:
        data = json.load(r)
    adm = data["admission"]
    for key in ("queue_depth", "queue_max", "tenants_waiting",
                "drain_per_s", "saturation", "fair"):
        assert key in adm, adm
    assert adm["queue_depth"] >= 0
