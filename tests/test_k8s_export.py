"""K8s manifest rendering: resources -> GKE TPU YAML (gitops path)."""

import subprocess
import sys

import yaml

from arks_tpu.control.k8s_export import (
    TPU_SHAPES, render_application, render_disaggregated, render_endpoint,
    render_model,
)
from arks_tpu.control.resources import (
    Application, DisaggregatedApplication, Endpoint, Model,
)


def _app(accelerator="tpu-v5e-16", replicas=2):
    return Application(name="q7b", namespace="team-a", spec={
        "replicas": replicas, "runtime": "jax", "accelerator": accelerator,
        "model": {"name": "qwen25"}, "servedModelName": "qwen2.5-7b",
        "modelConfig": "qwen2.5-7b", "tensorParallel": 4,
        "runtimeCommonArgs": ["--num-slots", "64"],
    })


def test_application_renders_gangs_with_tpu_topology():
    docs = render_application(_app())
    sets = [d for d in docs if d["kind"] == "StatefulSet"]
    assert len(sets) == 2  # one gang per replica
    shape = TPU_SHAPES["tpu-v5e-16"]
    for ss in sets:
        assert ss["spec"]["replicas"] == shape.hosts
        assert ss["spec"]["podManagementPolicy"] == "Parallel"
        pod = ss["spec"]["template"]["spec"]
        assert pod["nodeSelector"]["cloud.google.com/gke-tpu-accelerator"] \
            == shape.accelerator
        assert pod["nodeSelector"]["cloud.google.com/gke-tpu-topology"] \
            == shape.topology
        c = pod["containers"][0]
        assert c["resources"]["limits"]["google.com/tpu"] == str(shape.chips_per_host)
        env = {e["name"]: e for e in c["env"]}
        # JAX rendezvous contract (LWS env translation).
        assert env["ARKS_NUM_PROCESSES"]["value"] == str(shape.hosts)
        assert "ARKS_COORDINATOR_ADDRESS" in env
        assert "pod-index" in str(env["ARKS_PROCESS_ID"])
        # Reserved /models mount, read-only.
        mount = c["volumeMounts"][0]
        assert mount["mountPath"] == "/models" and mount["readOnly"]
        # Real entrypoint flags.
        assert c["args"][:2] == ["-m", "arks_tpu.server"]
        assert "--tensor-parallel-size" in c["args"]
        # Traffic gating: the front Service selects every gang pod, so the
        # readiness probe must be the leader-only endpoint.
        assert c["readinessProbe"]["httpGet"]["path"] == "/readiness"

    # Front service parity: arks-application-<name>, prometheus-discovery.
    front = [d for d in docs if d["kind"] == "Service"
             and d["metadata"]["name"] == "arks-application-q7b"]
    assert front and front[0]["metadata"]["labels"]["prometheus-discovery"] == "true"


def test_application_honors_model_storage_overrides():
    model = Model(name="qwen25", namespace="team-a", spec={
        "model": "Qwen/Qwen2.5-7B-Instruct",
        "storage": {"pvc": "shared-models", "subPath": "qwen"},
    })
    docs = render_application(_app(), model)
    ss = [d for d in docs if d["kind"] == "StatefulSet"][0]
    pod = ss["spec"]["template"]["spec"]
    assert pod["volumes"][0]["persistentVolumeClaim"]["claimName"] == "shared-models"
    c = pod["containers"][0]
    assert c["args"][c["args"].index("--model-path") + 1] == "/models/qwen"
    # And render_model provisions the same claim.
    assert render_model(model)[0]["metadata"]["name"] == "shared-models"


def test_disaggregated_renders_tiers_and_router():
    dapp = DisaggregatedApplication(name="pd", namespace="team-a", spec={
        "runtime": "jax", "model": {"name": "qwen25"},
        "servedModelName": "qwen2.5-7b", "modelConfig": "qwen2.5-7b",
        "router": {"replicas": 1, "port": 8080},
        "prefill": {"replicas": 2, "accelerator": "tpu-v5e-8"},
        "decode": {"replicas": 3, "accelerator": "tpu-v5e-8"},
    })
    docs = render_disaggregated(dapp)
    sets = [d for d in docs if d["kind"] == "StatefulSet"]
    assert len(sets) == 5  # 2 prefill + 3 decode gangs
    modes = [d["spec"]["template"]["spec"]["containers"][0]["args"] for d in sets]
    assert sum("prefill" in a for a in modes) == 2
    assert sum("decode" in a for a in modes) == 3
    router = [d for d in docs if d["kind"] == "Deployment"][0]
    env = {e["name"]: e["value"] for e in
           router["spec"]["template"]["spec"]["containers"][0]["env"]}
    assert env["ARKS_PREFILL_ADDRS"].startswith("arks-pd-prefill.team-a.svc:")
    assert env["ARKS_DECODE_ADDRS"].startswith("arks-pd-decode.team-a.svc:")
    # Router front service uses the standalone-app naming so endpoints
    # route to both kinds alike.
    assert any(d["kind"] == "Service"
               and d["metadata"]["name"] == "arks-application-pd" for d in docs)


def test_cpu_application_has_no_tpu_fields():
    docs = render_application(_app(accelerator="cpu", replicas=1))
    pod = [d for d in docs if d["kind"] == "StatefulSet"][0]["spec"]["template"]["spec"]
    assert "nodeSelector" not in pod
    assert "resources" not in pod["containers"][0]


def test_model_renders_pvc_and_download_job():
    m = Model(name="qwen25", namespace="team-a", spec={
        "model": "Qwen/Qwen2.5-7B-Instruct",
        "source": {"huggingface": {"tokenSecretRef": "hf-token"}},
    })
    docs = render_model(m)
    kinds = [d["kind"] for d in docs]
    assert kinds == ["PersistentVolumeClaim", "Job"]
    job = docs[1]["spec"]["template"]["spec"]["containers"][0]
    env = {e["name"]: e for e in job["env"]}
    assert env["MODEL_NAME"]["value"] == "Qwen/Qwen2.5-7B-Instruct"
    assert env["MODEL_PATH"]["value"].startswith("/models/")
    assert env["HF_TOKEN"]["valueFrom"]["secretKeyRef"]["name"] == "hf-token"
    assert env["ARKS_CONVERT_ORBAX"]["value"] == "1"


def test_model_without_source_renders_storage_only():
    docs = render_model(Model(name="pre", namespace="x", spec={"model": "m"}))
    assert [d["kind"] for d in docs] == ["PersistentVolumeClaim"]


def test_endpoint_renders_httproute_with_header_matches():
    ep = Endpoint(name="qwen2.5-7b", namespace="team-a",
                  spec={"defaultWeight": 3})
    docs = render_endpoint(ep, [_app()])
    route = docs[0]
    assert route["kind"] == "HTTPRoute"
    rule = route["spec"]["rules"][0]
    headers = {h["name"]: h["value"] for h in rule["matches"][0]["headers"]}
    assert headers == {"x-arks-namespace": "team-a",
                       "x-arks-model": "qwen2.5-7b"}
    assert rule["backendRefs"] == [{"name": "arks-application-q7b",
                                    "port": 8080, "weight": 3}]


def test_endpoint_skips_other_models_and_namespaces():
    ep = Endpoint(name="another-model", namespace="team-a", spec={})
    docs = render_endpoint(ep, [_app()])
    assert docs[0]["spec"]["rules"][0]["backendRefs"] == []
    # Same model name in a different namespace must NOT be routed.
    ep2 = Endpoint(name="qwen2.5-7b", namespace="team-b", spec={})
    docs = render_endpoint(ep2, [_app()])
    assert docs[0]["spec"]["rules"][0]["backendRefs"] == []


def test_endpoint_static_route_configs_become_backend_refs():
    ep = Endpoint(name="qwen2.5-7b", namespace="team-a", spec={
        "routeConfigs": [{"backend": {"service": "ext-svc", "port": 9000},
                          "weight": 2}]})
    docs = render_endpoint(ep, [])
    assert docs[0]["spec"]["rules"][0]["backendRefs"] == [
        {"name": "ext-svc", "port": 9000, "weight": 2}]


def test_cli_renders_quickstart():
    out = subprocess.run(
        [sys.executable, "-m", "arks_tpu.control.k8s_export",
         "--manifests", "examples/quickstart/quickstart.yaml"],
        capture_output=True, text=True, timeout=120)
    assert out.returncode == 0, out.stderr
    docs = list(yaml.safe_load_all(out.stdout))
    assert any(d["kind"] == "StatefulSet" for d in docs)
    assert any(d["kind"] == "HTTPRoute" for d in docs)


# ---------------------------------------------------------------------------
# InstanceSpec passthrough + gang scheduling
# ---------------------------------------------------------------------------


def _inst_spec():
    return {
        "env": [{"name": "HF_HOME", "value": "/tmp/hf"}],
        "resources": {"requests": {"memory": "100Gi"},
                      "limits": {"memory": "120Gi"}},
        "labels": {"team": "a"},
        "annotations": {"example.com/note": "x"},
        "volumes": [{"name": "scratch", "emptyDir": {}}],
        "volumeMounts": [{"name": "scratch", "mountPath": "/scratch"}],
        "nodeSelector": {"pool": "tpu"},
        "tolerations": [{"key": "google.com/tpu", "operator": "Exists"}],
        "initContainers": [{"name": "warmup", "image": "busybox",
                            "command": ["true"]}],
        "livenessProbe": {"httpGet": {"path": "/health", "port": 8080}},
        "serviceAccountName": "arks-engine",
        "terminationGracePeriodSeconds": 30,
    }


def test_instance_spec_passthrough():
    app = _app()
    app.spec["instanceSpec"] = _inst_spec()
    ss = [d for d in render_application(app) if d["kind"] == "StatefulSet"][0]
    tmpl = ss["spec"]["template"]
    pod = tmpl["spec"]
    c = pod["containers"][0]
    env = {e["name"]: e.get("value") for e in c["env"]}
    assert env["HF_HOME"] == "/tmp/hf"
    # User resources merged, TPU chips still owned by the accelerator shape.
    assert c["resources"]["requests"]["memory"] == "100Gi"
    assert c["resources"]["requests"]["google.com/tpu"] == "4"
    assert c["resources"]["limits"]["google.com/tpu"] == "4"
    # Volumes appended after the reserved models volume.
    assert [v["name"] for v in pod["volumes"]] == ["models", "scratch"]
    assert {"name": "scratch", "mountPath": "/scratch"} in c["volumeMounts"]
    # TPU nodeSelector keys win over user selector; user keys survive.
    assert pod["nodeSelector"]["pool"] == "tpu"
    assert pod["nodeSelector"]["cloud.google.com/gke-tpu-accelerator"]
    assert pod["tolerations"][0]["key"] == "google.com/tpu"
    assert pod["initContainers"][0]["name"] == "warmup"
    assert pod["serviceAccountName"] == "arks-engine"
    assert pod["terminationGracePeriodSeconds"] == 30
    assert c["livenessProbe"]["httpGet"]["path"] == "/health"
    assert tmpl["metadata"]["labels"]["team"] == "a"
    assert tmpl["metadata"]["annotations"]["example.com/note"] == "x"


def test_instance_spec_reserved_names_rejected():
    import pytest

    from arks_tpu.control.k8s_export import validate_instance_spec
    with pytest.raises(ValueError, match="reserved"):
        validate_instance_spec({"volumes": [{"name": "models"}]})
    with pytest.raises(ValueError, match="reserved"):
        validate_instance_spec(
            {"volumeMounts": [{"name": "x", "mountPath": "/models"}]})
    with pytest.raises(ValueError, match="reserved"):
        validate_instance_spec(
            {"env": [{"name": "ARKS_PROCESS_ID", "value": "7"}]})


def test_instance_spec_changes_revision():
    plain = [d for d in render_application(_app())
             if d["kind"] == "StatefulSet"][0]
    app = _app()
    app.spec["instanceSpec"] = {"env": [{"name": "A", "value": "1"}]}
    changed = [d for d in render_application(app)
               if d["kind"] == "StatefulSet"][0]
    rev = lambda s: s["spec"]["template"]["metadata"]["annotations"]["arks.ai/revision"]  # noqa: E731
    assert rev(plain) != rev(changed)


def test_pod_group_policy_kube_scheduling():
    app = _app()
    app.spec["podGroupPolicy"] = {"kubeScheduling": {}}
    docs = render_application(app)
    pgs = [d for d in docs if d["kind"] == "PodGroup"]
    assert len(pgs) == 2  # one per replica gang
    shape = TPU_SHAPES["tpu-v5e-16"]
    for pg in pgs:
        assert pg["apiVersion"] == "scheduling.x-k8s.io/v1alpha1"
        assert pg["spec"]["minMember"] == shape.hosts  # all-or-nothing slice
        assert pg["spec"]["scheduleTimeoutSeconds"] == 60  # reference default
    ss = [d for d in docs if d["kind"] == "StatefulSet"][0]
    labels = ss["spec"]["template"]["metadata"]["labels"]
    assert labels["scheduling.x-k8s.io/pod-group"] == ss["metadata"]["name"]


def test_pod_group_policy_volcano():
    app = _app(replicas=1)
    app.spec["podGroupPolicy"] = {"volcanoScheduling": {
        "queue": "tpu-high", "priorityClassName": "prod"}}
    docs = render_application(app)
    pg = [d for d in docs if d["kind"] == "PodGroup"][0]
    assert pg["apiVersion"] == "scheduling.volcano.sh/v1beta1"
    assert pg["spec"]["queue"] == "tpu-high"
    assert pg["spec"]["priorityClassName"] == "prod"
    ss = [d for d in docs if d["kind"] == "StatefulSet"][0]
    tmpl = ss["spec"]["template"]
    assert tmpl["spec"]["schedulerName"] == "volcano"
    assert tmpl["metadata"]["annotations"]["scheduling.k8s.io/group-name"] \
        == ss["metadata"]["name"]


def test_pod_group_policy_one_of():
    import pytest

    from arks_tpu.control.k8s_export import validate_pod_group_policy
    with pytest.raises(ValueError, match="exactly one"):
        validate_pod_group_policy({"kubeScheduling": {},
                                   "volcanoScheduling": {}})
    with pytest.raises(ValueError, match="exactly one"):
        validate_pod_group_policy({"unknown": {}})


def test_disagg_tier_instance_spec_and_router_args():
    dapp = DisaggregatedApplication(name="pd", namespace="team-a", spec={
        "runtime": "jax", "model": {"name": "qwen25"},
        "servedModelName": "qwen2.5-7b", "modelConfig": "qwen2.5-7b",
        "prefill": {"replicas": 1, "accelerator": "tpu-v5e-8",
                    "instanceSpec": {"labels": {"tier": "prefill"}}},
        "decode": {"replicas": 1, "accelerator": "tpu-v5e-8"},
        "router": {"replicas": 1, "routerArgs": ["--policy", "cache_aware"],
                   "instanceSpec": {"env": [{"name": "RUST_LOG",
                                             "value": "info"}]}},
    })
    docs = render_disaggregated(dapp)
    prefill = [d for d in docs if d["kind"] == "StatefulSet"
               and "prefill" in d["metadata"]["name"]][0]
    assert prefill["spec"]["template"]["metadata"]["labels"]["tier"] == "prefill"
    decode = [d for d in docs if d["kind"] == "StatefulSet"
              and "decode" in d["metadata"]["name"]][0]
    assert "tier" not in decode["spec"]["template"]["metadata"]["labels"]
    router = [d for d in docs if d["kind"] == "Deployment"][0]
    rc = router["spec"]["template"]["spec"]["containers"][0]
    assert {"name": "RUST_LOG", "value": "info"} in rc["env"]
    assert "cache_aware" in rc["args"]


def test_unified_mode_renders_one_unit_podgroup():
    """Unified layout (reference generateUnifiedRBGS :1265-1326): ONE
    PodGroup spans scheduler + prefill + decode — the whole PD unit
    schedules atomically."""
    dapp = DisaggregatedApplication(name="updd", namespace="team-a", spec={
        "runtime": "jax", "model": {"name": "qwen25"},
        "servedModelName": "qwen2.5-7b", "modelConfig": "qwen2.5-7b",
        "mode": "unified",
        "podGroupPolicy": {"kubeScheduling": {}},
        "prefill": {"replicas": 2, "accelerator": "tpu-v5e-16"},  # 4 hosts ea
        "decode": {"replicas": 1, "accelerator": "tpu-v5e-8"},    # 1 host
        "router": {"replicas": 1},
    })
    docs = render_disaggregated(dapp)
    pgs = [d for d in docs if d["kind"] == "PodGroup"]
    assert len(pgs) == 1
    assert pgs[0]["metadata"]["name"] == "arks-updd"
    # 2 prefill groups x 4 hosts + 1 decode group x 1 host + 1 router pod.
    assert pgs[0]["spec"]["minMember"] == 10
    # Every tier pod AND the router carry the unit marker.
    for d in docs:
        if d["kind"] in ("StatefulSet", "Deployment"):
            labels = d["spec"]["template"]["metadata"]["labels"]
            assert labels.get("scheduling.x-k8s.io/pod-group") == "arks-updd", \
                d["metadata"]["name"]


def test_legacy_mode_keeps_per_group_podgroups():
    dapp = DisaggregatedApplication(name="lgdd", namespace="team-a", spec={
        "runtime": "jax", "model": {"name": "qwen25"},
        "servedModelName": "qwen2.5-7b", "modelConfig": "qwen2.5-7b",
        "podGroupPolicy": {"kubeScheduling": {}},
        "prefill": {"replicas": 2, "accelerator": "tpu-v5e-16"},
        "decode": {"replicas": 1, "accelerator": "tpu-v5e-8"},
    })
    docs = render_disaggregated(dapp)
    pgs = sorted(d["metadata"]["name"] for d in docs if d["kind"] == "PodGroup")
    # One per tier group, none for the unit or the router.
    assert pgs == ["arks-lgdd-decode-0", "arks-lgdd-prefill-0",
                   "arks-lgdd-prefill-1"]


def test_unified_mode_without_podgroup_policy():
    dapp = DisaggregatedApplication(name="np", namespace="team-a", spec={
        "runtime": "jax", "model": {"name": "qwen25"},
        "servedModelName": "m", "modelConfig": "qwen2.5-7b",
        "mode": "unified",
        "prefill": {"replicas": 1}, "decode": {"replicas": 1},
    })
    docs = render_disaggregated(dapp)
    assert not [d for d in docs if d["kind"] == "PodGroup"]


def test_invalid_mode_rejected():
    import pytest
    dapp = DisaggregatedApplication(name="bad", namespace="team-a", spec={
        "runtime": "jax", "model": {"name": "qwen25"},
        "servedModelName": "m", "mode": "sideways",
    })
    with pytest.raises(ValueError, match="legacy|unified"):
        render_disaggregated(dapp)


def test_runtime_image_env_hatches(monkeypatch):
    """ARKS_RUNTIME_DEFAULT_*_IMAGE / ARKS_SCRIPTS_IMAGE escape hatches
    (reference arksapplication_controller.go:907-939, arksmodel_controller
    .go:369-375): spec wins > env > built-in default."""
    from arks_tpu.control.k8s_export import render_application, render_model
    from arks_tpu.control.resources import Application, Model
    from arks_tpu.control.workloads import default_runtime_image

    # Built-in defaults: jax image native; GPU runtimes mirror the
    # reference's pinned defaults.
    assert default_runtime_image("jax") == "arks-tpu/engine:latest"
    assert default_runtime_image("vllm").startswith("vllm/vllm-openai")
    assert default_runtime_image("sglang").startswith("lmsysorg/sglang")

    monkeypatch.setenv("ARKS_RUNTIME_DEFAULT_JAX_IMAGE", "reg.io/jax:v9")
    monkeypatch.setenv("ARKS_RUNTIME_DEFAULT_VLLM_IMAGE", "reg.io/vllm:v9")
    monkeypatch.setenv("ARKS_SCRIPTS_IMAGE", "reg.io/scripts:v9")
    assert default_runtime_image("jax") == "reg.io/jax:v9"
    assert default_runtime_image("vllm") == "reg.io/vllm:v9"

    app = Application(name="a1", spec={
        "replicas": 1, "size": 1, "runtime": "jax",
        "model": {"name": "m1"}, "servedModelName": "s",
        "modelConfig": "tiny"})
    docs = render_application(app)
    sts = next(d for d in docs if d["kind"] == "StatefulSet")
    img = sts["spec"]["template"]["spec"]["containers"][0]["image"]
    assert img == "reg.io/jax:v9"

    # spec.runtimeImage still wins over the env hatch.
    app2 = Application(name="a2", spec={
        "replicas": 1, "size": 1, "runtime": "jax",
        "model": {"name": "m1"}, "servedModelName": "s",
        "modelConfig": "tiny", "runtimeImage": "custom:1"})
    docs2 = render_application(app2)
    sts2 = next(d for d in docs2 if d["kind"] == "StatefulSet")
    assert sts2["spec"]["template"]["spec"]["containers"][0]["image"] == "custom:1"

    mdocs = render_model(Model(name="m1", spec={
        "model": "org/m", "source": {"huggingface": {}}}))
    job = next(d for d in mdocs if d["kind"] == "Job")
    assert (job["spec"]["template"]["spec"]["containers"][0]["image"]
            == "reg.io/scripts:v9")


def test_multislice_application_renders_dcn_gang():
    """"tpu-v5e-16x2": per-replica gang spans BOTH slices (8 pods), the
    rendezvous contract counts every host, ARKS_NUM_SLICES rides the env,
    and pods still select the per-slice node pool (each pod lives inside
    one slice; only the 'slice' mesh axis crosses DCN)."""
    docs = render_application(_app(accelerator="tpu-v5e-16x2", replicas=1))
    sets = [d for d in docs if d["kind"] == "StatefulSet"]
    assert len(sets) == 1
    ss = sets[0]
    base = TPU_SHAPES["tpu-v5e-16"]
    assert ss["spec"]["replicas"] == base.hosts * 2
    pod = ss["spec"]["template"]["spec"]
    assert pod["nodeSelector"]["cloud.google.com/gke-tpu-topology"] \
        == base.topology
    env = {e["name"]: e for e in pod["containers"][0]["env"]}
    assert env["ARKS_NUM_PROCESSES"]["value"] == str(base.hosts * 2)
    assert env["ARKS_NUM_SLICES"]["value"] == "2"
    assert "ARKS_COORDINATOR_ADDRESS" in env


def test_unknown_accelerator_suggests_multislice_syntax():
    import pytest as _pytest

    from arks_tpu.control.k8s_export import _shape
    with _pytest.raises(ValueError, match="multi-slice"):
        _shape("tpu-v9z-64")
    shape = _shape("tpu-v5p-16x2")
    assert shape.slices == 2 and shape.total_hosts == 4
