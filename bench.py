"""Decode-throughput benchmark on real hardware.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
Baseline: the north-star target of 2,000 tok/s/chip (BASELINE.md — the
reference publishes no numbers of its own).

Measures the fused multi-step decode loop (K decode steps + greedy sampling
inside one jitted scan) — one dispatch per K tokens, host transfer limited to
sampled ids.  This is the same shape the serving engine runs, and the only
honest way to time on a tunneled PJRT platform where per-dispatch latency
dominates and block_until_ready can return early.

Env knobs: ARKS_BENCH_MODEL (default qwen2.5-1.5b), ARKS_BENCH_BATCH,
ARKS_BENCH_CACHE_LEN, ARKS_BENCH_STEPS, ARKS_BENCH_TRIALS,
ARKS_BENCH_KV_DTYPE (int8|bf16, default int8 — matching the engine's
kv_cache_dtype=auto resolution on TPU).
"""

from __future__ import annotations

import json
import os
import time

import jax
import jax.numpy as jnp
import numpy as np

BASELINE_TOK_S_CHIP = 2000.0


def main() -> None:
    from arks_tpu.models import get_config
    from arks_tpu.models import transformer as tf

    model = os.environ.get("ARKS_BENCH_MODEL", "qwen2.5-1.5b")
    batch = int(os.environ.get("ARKS_BENCH_BATCH", "128"))
    cache_len = int(os.environ.get("ARKS_BENCH_CACHE_LEN", "1024"))
    steps = int(os.environ.get("ARKS_BENCH_STEPS", "32"))
    trials = int(os.environ.get("ARKS_BENCH_TRIALS", "3"))
    # int8 KV is the production serving default on TPU: ~12% faster decode
    # and 2x cache capacity at a bounded precision cost (see
    # tests/test_pallas_attention.py int8 tolerances).
    kv_dtype = os.environ.get("ARKS_BENCH_KV_DTYPE", "int8")
    kv_quant = kv_dtype == "int8"

    cfg = get_config(model)
    n_chips = len(jax.devices())
    mesh = None
    if n_chips > 1:
        from arks_tpu.parallel.mesh import make_mesh
        mesh = make_mesh(tensor_parallel=n_chips)

    params = tf.init_params(cfg, jax.random.PRNGKey(0))
    if mesh is not None:
        params = tf.shard_params(params, cfg, mesh)
    cache = tf.init_cache(cfg, num_slots=batch, max_len=cache_len,
                          quantized=kv_quant)

    def multi_step(params, cache, tokens, lengths):
        def body(carry, _):
            cache, tokens, lengths = carry
            logits, cache = tf.decode_step(params, cfg, cache, tokens, lengths, mesh)
            nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            return (cache, nxt, lengths + 1), nxt
        (cache, tokens, lengths), out = jax.lax.scan(
            body, (cache, tokens, lengths), None, length=steps)
        return cache, tokens, lengths, out

    fn = jax.jit(multi_step, donate_argnums=(1,))
    tokens = jnp.zeros((batch,), jnp.int32)
    # Mid-cache lengths: each decode step attends ~cache_len/2 of KV,
    # a representative steady-state working set.
    lengths = jnp.full((batch,), cache_len // 2, jnp.int32)

    # Warmup / compile.
    cache, tokens, lengths, out = fn(params, cache, tokens, lengths)
    np.asarray(out[-1])

    best = float("inf")
    for _ in range(trials):
        lengths = jnp.full((batch,), cache_len // 2, jnp.int32)
        t0 = time.perf_counter()
        cache, tokens, lengths, out = fn(params, cache, tokens, lengths)
        np.asarray(out[-1])  # host fetch of sampled ids = completion barrier
        best = min(best, time.perf_counter() - t0)

    tok_s_chip = batch * steps / best / max(n_chips, 1)
    print(json.dumps({
        "metric": f"decode_throughput_{model}_b{batch}_kv-{kv_dtype}",
        "value": round(tok_s_chip, 1),
        "unit": "tok/s/chip",
        "vs_baseline": round(tok_s_chip / BASELINE_TOK_S_CHIP, 3),
    }))


if __name__ == "__main__":
    main()
