"""North-star benchmark on real hardware: Qwen2.5-7B on one TPU chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...extras}.
Baseline: BASELINE.md north star — >=2,000 tok/s/chip decode throughput AND
p50 TTFT < 200 ms on Qwen2.5-7B (the reference publishes no numbers of its
own; these targets come from BASELINE.json).  ``vs_baseline`` is computed on
this 7B config — not on a smaller stand-in.

Configuration mirrors the production serving defaults on a 16GB v5e chip:
int8 weight-only quantization (w8a16 — bf16 weights alone are ~15GB and do
not fit next to a KV cache; see arks_tpu/models/quant.py) and int8 KV cache
(the engine's kv_cache_dtype=auto resolution on TPU).

Two measurements:
- Decode throughput: the fused multi-step decode loop (K decode steps +
  greedy sampling inside one jitted scan) — one dispatch per K tokens, host
  transfer limited to sampled ids.  This is the same shape the serving
  engine runs, and the only honest way to time on a tunneled PJRT platform
  where per-dispatch latency dominates and block_until_ready can return
  early.
- TTFT: single-prompt prefill (bucketed length) + first-token argmax, host
  fetch of the sampled id as the completion barrier; p50 over trials.

Env knobs: ARKS_BENCH_MODEL (default qwen2.5-7b), ARKS_BENCH_BATCH,
ARKS_BENCH_CACHE_LEN, ARKS_BENCH_STEPS, ARKS_BENCH_TRIALS,
ARKS_BENCH_PROMPT_LEN (TTFT prompt length, default 1024),
ARKS_BENCH_KV_DTYPE (int8|bf16), ARKS_BENCH_WEIGHT_DTYPE (int8|bf16).
"""

from __future__ import annotations

import functools
import json
import os
import time

import jax
import jax.numpy as jnp
import numpy as np

BASELINE_TOK_S_CHIP = 2000.0
TARGET_TTFT_MS = 200.0


def pallas_parity_check(kv_quant: bool) -> float:
    """On-device parity: the Pallas decode path (cache update + ragged
    attention) vs the XLA oracle on the same random inputs — the compiled-TPU
    counterpart of the interpret-mode unit tests (tests/
    test_pallas_attention.py necessarily run interpret on CPU).  Returns the
    max |pallas - xla| over the attention output; the shapes satisfy the
    kernel tiling constraints (S % 256, B % 16)."""
    from arks_tpu.ops.attention import decode_update_and_attend

    L, B, Hkv, G, S, D = 2, 16, 4, 7, 512, 128
    key = jax.random.PRNGKey(0)
    ks = jax.random.split(key, 8)
    q = jax.random.normal(ks[0], (B, Hkv * G, D), jnp.bfloat16)
    k_new = jax.random.normal(ks[1], (B, Hkv, D), jnp.bfloat16)
    v_new = jax.random.normal(ks[2], (B, Hkv, D), jnp.bfloat16)
    if kv_quant:
        kc = jax.random.randint(ks[3], (L, B, Hkv, S, D), -127, 128, jnp.int8)
        vc = jax.random.randint(ks[4], (L, B, Hkv, S, D), -127, 128, jnp.int8)
        kscale = jax.random.uniform(ks[5], (L, B, Hkv, S), jnp.float32, 0.01, 0.03)
        vscale = jax.random.uniform(ks[6], (L, B, Hkv, S), jnp.float32, 0.01, 0.03)
    else:
        kc = jax.random.normal(ks[3], (L, B, Hkv, S, D), jnp.bfloat16)
        vc = jax.random.normal(ks[4], (L, B, Hkv, S, D), jnp.bfloat16)
        kscale = vscale = None
    widx = jnp.arange(B, dtype=jnp.int32) * 17 % (S - 1)
    layer = jnp.asarray(1, jnp.int32)

    def run(impl):
        out, *_ = jax.jit(functools.partial(
            decode_update_and_attend, impl=impl))(
            q, k_new, v_new, kc, vc, widx, layer,
            k_scale=kscale, v_scale=vscale)
        return np.asarray(out, np.float32)

    diff = float(np.max(np.abs(run("pallas") - run("xla"))))

    # Lane-padded small-head case (head_dim 64 stored at 128): the padded
    # kernel path must agree with the XLA oracle on device too.
    Dp = 64
    qs = jax.random.normal(ks[7], (B, Hkv * G, Dp), jnp.bfloat16)
    kns = jax.random.normal(ks[0], (B, Hkv, Dp), jnp.bfloat16)
    vns = jax.random.normal(ks[1], (B, Hkv, Dp), jnp.bfloat16)
    if kv_quant:
        kcp = jax.random.randint(ks[2], (L, B, Hkv, S, 128), -127, 128, jnp.int8)
        vcp = jax.random.randint(ks[3], (L, B, Hkv, S, 128), -127, 128, jnp.int8)
        # Padded lanes must be ZERO (real caches only ever write padded
        # rows) — random int8 there would differ from the oracle's view.
        lane = jnp.arange(128) < Dp
        kcp = jnp.where(lane, kcp, 0)
        vcp = jnp.where(lane, vcp, 0)
        kvargs = dict(k_scale=kscale, v_scale=vscale)
    else:
        kcp = jnp.zeros((L, B, Hkv, S, 128), jnp.bfloat16)
        vcp = jnp.zeros((L, B, Hkv, S, 128), jnp.bfloat16)
        kvargs = dict(k_scale=None, v_scale=None)

    def run_pad(impl):
        out, *_ = jax.jit(functools.partial(
            decode_update_and_attend, impl=impl))(
            qs, kns, vns, kcp, vcp, widx, layer, **kvargs)
        return np.asarray(out, np.float32)

    pad_diff = float(np.max(np.abs(run_pad("pallas") - run_pad("xla"))))
    return max(diff, pad_diff)


def main() -> None:
    from arks_tpu.models import get_config
    from arks_tpu.models import quant
    from arks_tpu.models import transformer as tf

    model = os.environ.get("ARKS_BENCH_MODEL", "qwen2.5-7b")
    # 192 beats 128 by ~9% and keeps ~2GB more HBM headroom than 256 on a
    # 16GB v5e (256 was only ~1% faster than 192 when measured).
    batch = int(os.environ.get("ARKS_BENCH_BATCH", "192"))
    cache_len = int(os.environ.get("ARKS_BENCH_CACHE_LEN", "1024"))
    # K sensitivity (b192, measured): 32 -> 6.44k, 64 -> 6.66k, 128 -> 6.78k
    # tok/s/chip.  32 stays the default: it matches a serving-realistic
    # scheduler granularity; bigger K trades admission latency for the
    # last ~5% by amortizing dispatch overhead further.
    steps = int(os.environ.get("ARKS_BENCH_STEPS", "32"))
    trials = int(os.environ.get("ARKS_BENCH_TRIALS", "3"))
    prompt_len = int(os.environ.get("ARKS_BENCH_PROMPT_LEN", "1024"))
    ttft_trials = int(os.environ.get("ARKS_BENCH_TTFT_TRIALS", "9"))
    kv_dtype = os.environ.get("ARKS_BENCH_KV_DTYPE", "int8")
    weight_dtype = os.environ.get("ARKS_BENCH_WEIGHT_DTYPE", "int8")
    kv_quant = kv_dtype == "int8"

    cfg = get_config(model)
    n_chips = len(jax.devices())
    mesh = None
    if n_chips > 1:
        from arks_tpu.parallel.mesh import make_mesh
        mesh = make_mesh(tensor_parallel=n_chips)

    if weight_dtype == "int8":
        params = quant.init_params_quantized(cfg, jax.random.PRNGKey(0))
    else:
        params = tf.init_params(cfg, jax.random.PRNGKey(0))
    if mesh is not None:
        params = tf.shard_params(params, cfg, mesh)

    # ---- TTFT: bucketed single-prompt prefill + first-token argmax --------
    def first_token(params, tokens, lengths):
        logits, ks, vs = tf.prefill(params, cfg, tokens, lengths, mesh)
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)

    prefill_fn = jax.jit(first_token)
    toks = jnp.zeros((1, prompt_len), jnp.int32)
    lens = jnp.asarray([prompt_len], jnp.int32)
    np.asarray(prefill_fn(params, toks, lens))  # warmup/compile
    ttft_ms = []
    for _ in range(ttft_trials):
        t0 = time.perf_counter()
        np.asarray(prefill_fn(params, toks, lens))  # host fetch = barrier
        ttft_ms.append((time.perf_counter() - t0) * 1e3)
    ttft_p50 = float(np.percentile(ttft_ms, 50))

    # ---- Decode throughput: fused multi-step loop -------------------------
    cache = tf.init_cache(cfg, num_slots=batch, max_len=cache_len,
                          quantized=kv_quant)

    def multi_step(params, cache, tokens, lengths):
        def body(carry, _):
            cache, tokens, lengths = carry
            logits, cache = tf.decode_step(params, cfg, cache, tokens, lengths, mesh)
            nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            return (cache, nxt, lengths + 1), nxt
        (cache, tokens, lengths), out = jax.lax.scan(
            body, (cache, tokens, lengths), None, length=steps)
        return cache, tokens, lengths, out

    fn = jax.jit(multi_step, donate_argnums=(1,))
    tokens = jnp.zeros((batch,), jnp.int32)
    # Mid-cache lengths: each decode step attends ~cache_len/2 of KV,
    # a representative steady-state working set.
    lengths = jnp.full((batch,), cache_len // 2, jnp.int32)

    cache, tokens, lengths, out = fn(params, cache, tokens, lengths)
    np.asarray(out[-1])  # warmup/compile

    best = float("inf")
    for _ in range(trials):
        lengths = jnp.full((batch,), cache_len // 2, jnp.int32)
        t0 = time.perf_counter()
        cache, tokens, lengths, out = fn(params, cache, tokens, lengths)
        np.asarray(out[-1])  # host fetch of sampled ids = completion barrier
        best = min(best, time.perf_counter() - t0)

    tok_s_chip = batch * steps / best / max(n_chips, 1)

    # TPU-side kernel parity rides every bench run: the Pallas decode path
    # must agree with the XLA oracle ON DEVICE, not just in CPU interpret
    # mode.  bf16 accumulation + (for int8) requantization of the new row
    # bound the tolerance.
    parity_diff = pallas_parity_check(kv_quant)
    parity_ok = parity_diff < (0.075 if kv_quant else 0.05)

    # Serving-path numbers (engine + OpenAI server + SSE under concurrent
    # load — bench_serving.py): the honest counterpart of the raw-loop
    # number above.  Raw-bench device buffers are dropped first so the
    # serving engine's params+cache fit HBM alongside nothing.
    serving = {}
    if os.environ.get("ARKS_BENCH_SERVING", "1") != "0":
        import gc
        del params, cache, tokens, lengths, out, fn, prefill_fn
        gc.collect()
        try:
            from bench_serving import run_serving_bench
            serving = run_serving_bench(model)
        except Exception as e:  # the raw-loop numbers must still print
            import traceback
            traceback.print_exc()
            serving = {"serving_error": f"{type(e).__name__}: {e}"}

    print(json.dumps({
        "metric": f"decode_throughput_{model}_b{batch}_w-{weight_dtype}_kv-{kv_dtype}",
        "value": round(tok_s_chip, 1),
        "unit": "tok/s/chip",
        "vs_baseline": round(tok_s_chip / BASELINE_TOK_S_CHIP, 3),
        "ttft_p50_ms": round(ttft_p50, 1),
        "ttft_prompt_len": prompt_len,
        "ttft_vs_target": round(TARGET_TTFT_MS / ttft_p50, 3),
        "pallas_parity_maxdiff": round(parity_diff, 5),
        "pallas_parity_ok": parity_ok,
        **serving,
    }))


if __name__ == "__main__":
    main()
