"""North-star benchmark on real hardware: Qwen2.5-7B on one TPU chip.

Output contract: the LAST stdout line is the result JSON
({"metric", "value", "unit", "vs_baseline", ...extras}).  A raw-loop
checkpoint line precedes the final combined line so a run killed mid-
serving still leaves parsable evidence; consumers must take the last
line, not parse the whole stream.
Baseline: BASELINE.md north star — >=2,000 tok/s/chip decode throughput AND
p50 TTFT < 200 ms on Qwen2.5-7B (the reference publishes no numbers of its
own; these targets come from BASELINE.json).  ``vs_baseline`` is computed on
this 7B config — not on a smaller stand-in.

Configuration mirrors the production serving defaults on a 16GB v5e chip:
int8 weight-only quantization (w8a16 — bf16 weights alone are ~15GB and do
not fit next to a KV cache; see arks_tpu/models/quant.py) and int8 KV cache
(the engine's kv_cache_dtype=auto resolution on TPU).

Two measurements:
- Decode throughput: the fused multi-step decode loop (K decode steps +
  greedy sampling inside one jitted scan) — one dispatch per K tokens, host
  transfer limited to sampled ids.  This is the same shape the serving
  engine runs, and the only honest way to time on a tunneled PJRT platform
  where per-dispatch latency dominates and block_until_ready can return
  early.
- TTFT: single-prompt prefill (bucketed length) + first-token argmax, host
  fetch of the sampled id as the completion barrier; p50 over trials.

Env knobs: ARKS_BENCH_MODEL (default qwen2.5-7b), ARKS_BENCH_BATCH,
ARKS_BENCH_CACHE_LEN, ARKS_BENCH_STEPS, ARKS_BENCH_TRIALS,
ARKS_BENCH_PROMPT_LEN (TTFT prompt length, default 1024),
ARKS_BENCH_KV_DTYPE (int8|bf16), ARKS_BENCH_WEIGHT_DTYPE (int8|bf16).
"""

from __future__ import annotations

import functools
import json
import os
import subprocess
import sys
import time

# Importing jax is safe before the probe — backend init is lazy (only
# jax.devices()/first dispatch touches the tunnel).
import jax

# This image's sitecustomize imports jax at interpreter startup under the
# default platform, so the JAX_PLATFORMS env var alone is TOO LATE by the
# time bench.py runs — apply it through jax.config (same trick as
# tests/conftest.py).  Without this, a CPU run of the bench would still
# probe the TPU tunnel and hang when it is down.
if os.environ.get("JAX_PLATFORMS"):
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

import jax.numpy as jnp
import numpy as np

BASELINE_TOK_S_CHIP = 2000.0
TARGET_TTFT_MS = 200.0


_PROBE_CODE = ("import os, jax\n"
               "p = os.environ.get('JAX_PLATFORMS')\n"
               "if p: jax.config.update('jax_platforms', p)\n"
               "print(len(jax.devices()))\n")


def probe_backend(timeout_s: float = 180.0, attempts: int = 3,
                  backoff_s: float = 10.0,
                  deadline_s: float | None = None,
                  max_backoff_s: float = 120.0,
                  code: str | None = None) -> tuple[bool, str]:
    """Probe JAX backend init in a SUBPROCESS with a timeout.  Backend init
    on a tunneled TPU platform can *hang forever* (not just raise) when the
    tunnel is down — probing in-process would mean the driver gets a
    timeout and no JSON at all.  Returns (ok, last_error).

    Two retry regimes:
    - ``deadline_s`` set (the default run mode, ARKS_BENCH_PROBE_DEADLINE_S
      ~3600): keep probing with capped exponential backoff until the
      backend answers or the deadline passes — a tunnel that flaps for half
      an hour still yields a REAL bench run instead of a 0.0 record (the
      round-4/5 failure mode: three rounds of evidence lost to 3x180s
      give-ups).
    - ``deadline_s`` None: the legacy fixed-attempts loop (kept for quick
      probes and tests).

    ``code`` overrides the probed snippet (tests simulate an initially-
    unreachable backend with it)."""
    last = ""
    # The probe must target the SAME platform the bench will use; the
    # sitecustomize-imported jax ignores a late JAX_PLATFORMS env var, so
    # route it through jax.config (see the module-level note).
    code = code if code is not None else _PROBE_CODE
    start = time.monotonic()
    delay = backoff_s
    attempt = 0
    while True:
        attempt += 1
        try:
            r = subprocess.run(
                [sys.executable, "-c", code],
                capture_output=True, text=True, timeout=timeout_s)
            if r.returncode == 0:
                return True, ""
            last = (r.stderr or r.stdout).strip().splitlines()[-1][-500:] \
                if (r.stderr or r.stdout).strip() else f"rc={r.returncode}"
        except subprocess.TimeoutExpired:
            last = f"backend init hung past {timeout_s:.0f}s (tunnel down?)"
        if deadline_s is not None:
            elapsed = time.monotonic() - start
            if elapsed + delay >= deadline_s:
                return False, last
            print(f"# backend probe attempt {attempt} failed: {last}; "
                  f"retrying in {delay:.0f}s "
                  f"({deadline_s - elapsed:.0f}s left in probe window)",
                  file=sys.stderr, flush=True)
            time.sleep(delay)
            delay = min(delay * 2, max_backoff_s)
            continue
        if attempt >= attempts:
            return False, last
        print(f"# backend probe {attempt}/{attempts} failed: {last}; "
              f"retrying in {backoff_s:.0f}s", file=sys.stderr, flush=True)
        time.sleep(backoff_s)


def pallas_parity_check(kv_quant: bool) -> float:
    """On-device parity: the Pallas decode path (cache update + ragged
    attention) vs the XLA oracle on the same random inputs — the compiled-TPU
    counterpart of the interpret-mode unit tests (tests/
    test_pallas_attention.py necessarily run interpret on CPU).  Returns the
    max |pallas - xla| over the attention output; the shapes satisfy the
    kernel tiling constraints (S % 256, B % 16)."""
    from arks_tpu.ops.attention import decode_update_and_attend

    L, B, Hkv, G, S, D = 2, 16, 4, 7, 512, 128
    key = jax.random.PRNGKey(0)
    ks = jax.random.split(key, 8)
    q = jax.random.normal(ks[0], (B, Hkv * G, D), jnp.bfloat16)
    k_new = jax.random.normal(ks[1], (B, Hkv, D), jnp.bfloat16)
    v_new = jax.random.normal(ks[2], (B, Hkv, D), jnp.bfloat16)
    if kv_quant:
        kc = jax.random.randint(ks[3], (L, B, Hkv, S, D), -127, 128, jnp.int8)
        vc = jax.random.randint(ks[4], (L, B, Hkv, S, D), -127, 128, jnp.int8)
        kscale = jax.random.uniform(ks[5], (L, B, Hkv, S), jnp.float32, 0.01, 0.03)
        vscale = jax.random.uniform(ks[6], (L, B, Hkv, S), jnp.float32, 0.01, 0.03)
    else:
        kc = jax.random.normal(ks[3], (L, B, Hkv, S, D), jnp.bfloat16)
        vc = jax.random.normal(ks[4], (L, B, Hkv, S, D), jnp.bfloat16)
        kscale = vscale = None
    widx = jnp.arange(B, dtype=jnp.int32) * 17 % (S - 1)
    layer = jnp.asarray(1, jnp.int32)

    def run(impl):
        out, *_ = jax.jit(functools.partial(
            decode_update_and_attend, impl=impl))(
            q, k_new, v_new, kc, vc, widx, layer,
            k_scale=kscale, v_scale=vscale)
        return np.asarray(out, np.float32)

    diff = float(np.max(np.abs(run("pallas") - run("xla"))))

    # Lane-padded small-head case (head_dim 64 stored at 128): the padded
    # kernel path must agree with the XLA oracle on device too.
    Dp = 64
    qs = jax.random.normal(ks[7], (B, Hkv * G, Dp), jnp.bfloat16)
    kns = jax.random.normal(ks[0], (B, Hkv, Dp), jnp.bfloat16)
    vns = jax.random.normal(ks[1], (B, Hkv, Dp), jnp.bfloat16)
    if kv_quant:
        kcp = jax.random.randint(ks[2], (L, B, Hkv, S, 128), -127, 128, jnp.int8)
        vcp = jax.random.randint(ks[3], (L, B, Hkv, S, 128), -127, 128, jnp.int8)
        # Padded lanes must be ZERO (real caches only ever write padded
        # rows) — random int8 there would differ from the oracle's view.
        lane = jnp.arange(128) < Dp
        kcp = jnp.where(lane, kcp, 0)
        vcp = jnp.where(lane, vcp, 0)
        kvargs = dict(k_scale=kscale, v_scale=vscale)
    else:
        kcp = jnp.zeros((L, B, Hkv, S, 128), jnp.bfloat16)
        vcp = jnp.zeros((L, B, Hkv, S, 128), jnp.bfloat16)
        kvargs = dict(k_scale=None, v_scale=None)

    def run_pad(impl):
        out, *_ = jax.jit(functools.partial(
            decode_update_and_attend, impl=impl))(
            qs, kns, vns, kcp, vcp, widx, layer, **kvargs)
        return np.asarray(out, np.float32)

    pad_diff = float(np.max(np.abs(run_pad("pallas") - run_pad("xla"))))
    return max(diff, pad_diff)


# GQA sweep shape: (hkv, d, page, max_pages, qmax).
_GQA_SHAPE = (8, 16, 16, 16, 64)
_GQA_VMEM_BUDGET = 18432  # f32 lanes; hg=1 affords block_q=qmax, hg=8 only 4


def _gqa_vmem_block_q(hg: int, g: int) -> int:
    """Largest q block the modeled VMEM budget affords one (hg-head,
    g-share) work item: double-buffered KV blocks (2 in flight) + q tile
    + f32 accumulator.  Grouping divides the whole footprint by
    hkv/head_group, which is the headroom the tuned plan re-invests in
    block_q."""
    hkv, d, page, _, qmax = _GQA_SHAPE
    comp = (_GQA_VMEM_BUDGET // hg - 4 * page * d) // (2 * g * d)
    if comp >= qmax:
        return qmax
    bq = 1
    while bq * 2 <= comp:
        bq *= 2
    return bq


def measure_gqa_bytes_sweep() -> dict:
    """GQA head-group sweep (g in {1, 4, 8}), plan-only — no kernel
    launches, so tests can gate on it cheaply.  The head-grouped DMA
    restructure wins KV bytes THROUGH block_q: grouping shrinks a work
    item's VMEM footprint by hkv/head_group, the tuned plan re-invests
    that headroom in a larger q block, and fewer q blocks re-stream each
    causal page prefix fewer times.  Emits the bytes-moved counter pair
    (mixed_kv_bytes actual vs fetch-each-block-once ideal) for the
    ungrouped baseline vs the grouped tuned plan; the g=8 row is the
    acceptance shape (ratio >= g)."""
    from arks_tpu.engine.paged import mixed_kv_bytes
    from arks_tpu.ops import paged_attention as pa

    hkv, d, page, maxp, qmax = _GQA_SHAPE
    # Decode-heavy lanes: a long causal prefix (the re-stream cost the
    # grouping exists to cut) plus a short second lane.
    pos = np.zeros(4, np.int32)
    ql = np.zeros(4, np.int32)
    pos[:2] = (maxp * page - qmax, page)
    ql[:2] = (qmax, 8)
    phb = page * d * 4 * 2  # f32 K + V bytes per (page, head) block
    out: dict = {}
    for g in (1, 4, 8):
        byt = {}
        for name, hg in (("base", hkv), ("grouped", 1)):
            plan = pa.mixed_grid_plan(
                qmax, hkv=hkv, g=g, d=d, page=page, kv="float32",
                block_q=_gqa_vmem_block_q(hg, g), grid="ragged",
                head_group=hg)
            b_act, b_ideal = mixed_kv_bytes(
                pos, ql, page=page, block_q=plan["block_q"],
                num_qb=plan["num_qb"], max_pages=maxp, hkv=hkv,
                page_head_bytes=phb)
            byt[name] = b_act
            out[f"gqa_g{g}_{name}_block_q"] = plan["block_q"]
            out[f"gqa_g{g}_{name}_kv_bytes"] = b_act
            out[f"gqa_g{g}_kv_bytes_ideal"] = b_ideal
        out[f"gqa_g{g}_bytes_ratio"] = round(byt["base"] / byt["grouped"],
                                             2)
    return out


def measure_kernel_microbench() -> dict:
    """Mixed-kernel microbench rung: dense vs ragged grid x int8 vs int4
    KV x default vs tuned block_q, on a SPARSE batch (3 active lanes of 8)
    — the shape the ragged work-list grid exists for.  Runs in interpret
    mode on CPU so the rung rides every bench round; interpret-mode
    timings order the work (grid steps executed), they are not TPU
    latencies — the grid_steps_* pair is the load-bearing number there.
    Under ARKS_KERNEL_TUNE=sweep the winning block_q is persisted to the
    autotune table, so a bench round doubles as the tuning pass."""
    from arks_tpu.engine.paged import mixed_grid_steps
    from arks_tpu.ops import autotune
    from arks_tpu.ops import paged_attention as pa
    from arks_tpu.ops.pallas_attention import quantize_kv

    on_tpu = jax.default_backend() == "tpu"
    interpret = not on_tpu
    s, hkv, g, maxp = 8, 2, 2, 4
    d = 128 if on_tpu else 32
    page = 128 if on_tpu else 16
    qmax = 8
    repeats = 3 if on_tpu else 2
    rng = np.random.default_rng(0)
    kf = jnp.asarray(rng.normal(size=(1, s * maxp, hkv, page, d)),
                     jnp.float32)
    vf = jnp.asarray(rng.normal(size=kf.shape), jnp.float32)
    k8, ks = quantize_kv(kf)
    v8, vs = quantize_kv(vf)
    k4q, k4s = quantize_kv(kf, qmax=7)
    v4q, v4s = quantize_kv(vf, qmax=7)
    pools = {
        "int8": (k8, v8, ks, vs),
        "int4": (pa.pack_int4(k4q, axis=3), pa.pack_int4(v4q, axis=3),
                 k4s, v4s),
    }
    tables = jnp.arange(s * maxp, dtype=jnp.int32).reshape(s, maxp)
    q = jnp.asarray(rng.normal(size=(s, hkv, g, qmax, d)), jnp.float32)
    # 3 active lanes (one full chunk, one mid-page decode burst, one
    # short), 5 idle — the padding the dense grid pays for.
    pos = np.zeros(s, np.int32)
    ql = np.zeros(s, np.int32)
    pos[:3], ql[:3] = (0, page + 3, 5), (qmax, qmax, 3)
    posj, qlj = jnp.asarray(pos), jnp.asarray(ql)

    def timeit(fn):
        fn()  # compile/warm outside the timed window
        t0 = time.perf_counter()
        for _ in range(repeats):
            fn()
        return round((time.perf_counter() - t0) / repeats * 1e3, 2)

    out: dict = {}
    for kv_name, (kp, vp, kss, vss) in pools.items():
        for grid in ("ragged", "dense"):
            def launch(block_q=None, dma_depth=None):
                r = pa.paged_mixed_attention(
                    q, kp, vp, tables, posj, qlj, 0, k_scale=kss,
                    v_scale=vss, block_q=block_q, interpret=interpret,
                    grid=grid, dma_depth=dma_depth)
                np.asarray(r)  # host fetch = completion barrier
            out[f"mixed_{grid}_{kv_name}_default_ms"] = timeit(launch)
            # Tuned: best block_q over the candidate set; a sweep-mode run
            # persists it under this shape's signature for serving reuse.
            cands = [{"block_q": b, "dma_depth": 2} for b in (2, qmax)]
            timed = {c["block_q"]: timeit(lambda c=c: launch(**c))
                     for c in cands}
            best_bq = min(timed, key=timed.get)
            out[f"mixed_{grid}_{kv_name}_tuned_ms"] = timed[best_bq]
            out[f"mixed_{grid}_{kv_name}_tuned_block_q"] = best_bq
            if grid == "ragged" and autotune.mode() == "sweep":
                autotune.record(
                    "paged_mixed",
                    autotune.mixed_signature(hkv=hkv, g=g, d=d, page=page,
                                             qmax=qmax, kv=kv_name),
                    {"block_q": best_bq, "dma_depth": 2})
    # The structural number (hardware-independent): page-compute steps the
    # ragged grid executes vs the dense grid's S*num_qb*max_pages padding.
    plan = pa.mixed_grid_plan(qmax, hkv=hkv, g=g, d=d, page=page, kv="int8")
    ideal, dense = mixed_grid_steps(pos, ql, page=page,
                                    block_q=plan["block_q"],
                                    num_qb=plan["num_qb"], max_pages=maxp)
    out["grid_steps_ideal"] = ideal
    out["grid_steps_dense"] = dense
    out.update(measure_gqa_bytes_sweep())

    # Kernel launches on the g=8 acceptance shape: all three schedules
    # (dense grid, ungrouped ragged, grouped ragged) must agree BITWISE,
    # and the grouped tuned plan times alongside.
    hkv8, d8, page8, maxp8, qmax8 = _GQA_SHAPE
    pos8 = np.zeros(4, np.int32)
    ql8 = np.zeros(4, np.int32)
    pos8[:2] = (maxp8 * page8 - qmax8, page8)
    ql8[:2] = (qmax8, 8)
    g8 = 8
    kf8 = jnp.asarray(rng.normal(size=(1, 2 * maxp8, hkv8, page8, d8)),
                      jnp.float32)
    vf8 = jnp.asarray(rng.normal(size=kf8.shape), jnp.float32)
    t8 = jnp.arange(2 * maxp8, dtype=jnp.int32).reshape(2, maxp8)
    q8 = jnp.asarray(rng.normal(size=(2, hkv8, g8, qmax8, d8)), jnp.float32)
    p8j, q8j = jnp.asarray(pos8[:2]), jnp.asarray(ql8[:2])

    def launch8(block_q, head_group, grid):
        r = pa.paged_mixed_attention(
            q8, kf8, vf8, t8, p8j, q8j, 0, block_q=block_q,
            interpret=interpret, grid=grid, head_group=head_group)
        return np.asarray(r)

    base_bq8, tuned_bq8 = _gqa_vmem_block_q(hkv8, g8), _gqa_vmem_block_q(1, g8)
    o_dense = launch8(base_bq8, hkv8, "dense")
    o_base = launch8(base_bq8, hkv8, "ragged")
    o_grp = launch8(tuned_bq8, 1, "ragged")
    out["gqa_g8_bitwise"] = bool(np.array_equal(o_dense, o_base)
                                 and np.array_equal(o_base, o_grp))
    out["gqa_g8_base_ms"] = timeit(
        lambda: launch8(base_bq8, hkv8, "ragged"))
    out["gqa_g8_grouped_ms"] = timeit(
        lambda: launch8(tuned_bq8, 1, "ragged"))
    return out


def measure_mixed_ttft_under_load() -> float:
    """p50 TTFT (ms) of chunk-length prompts admitted while EVERY decode
    slot is busy — the decode+prefill contention number the mixed scheduler
    (ARKS_MIXED_STEP) exists to bound: legacy chunking pays one extra full
    dispatch per chunk while all decode slots stall; the mixed step folds
    the chunk into the decode dispatch.

    Runs a real InferenceEngine (paged + mixed) at a small, fixed shape so
    the measurement rides every bench round without a second 7B init;
    ARKS_BENCH_MIXED_MODEL overrides (default qwen2.5-0.5b on TPU, tiny on
    CPU smoke runs)."""
    from arks_tpu.engine import EngineConfig, InferenceEngine
    from arks_tpu.engine.tokenizer import ByteTokenizer
    from arks_tpu.engine.types import Request, SamplingParams
    from arks_tpu.models import get_config

    on_tpu = jax.default_backend() == "tpu"
    model = os.environ.get("ARKS_BENCH_MIXED_MODEL",
                           "qwen2.5-0.5b" if on_tpu else "tiny")
    cfg = get_config(model)
    num_slots = int(os.environ.get("ARKS_BENCH_MIXED_SLOTS",
                                   "8" if on_tpu else "2"))
    chunk = 256 if on_tpu else 16
    ecfg = EngineConfig(model=model, num_slots=num_slots,
                        max_cache_len=1024 if on_tpu else 64,
                        prefill_buckets=(32, 64, 128, 256) if on_tpu
                        else (8, 16, 32),
                        steps_per_dispatch=4, prefill_chunk=chunk,
                        kv_layout="paged", prefix_cache_mb=0)
    eng = InferenceEngine(cfg, ecfg, ByteTokenizer())
    assert eng._mixed, "mixed step unexpectedly unsupported for the bench shape"
    eng.start()
    try:
        # Saturate all but one slot with long-running decodes (distinct
        # prompts so the prefix index never merges them); the probe takes
        # the last slot, its chunked prefill contending with the decodes.
        load = []
        for i in range(max(num_slots - 1, 1)):
            r = Request(f"load{i}", [3 + i, 7, 11],
                        SamplingParams(max_tokens=10_000, temperature=0.0,
                                       ignore_eos=True))
            load.append(r)
            eng.add_request(r)
        for r in load:
            r.outputs.get(timeout=300)  # first token = slot decoding
        # Chunk-length prompts admitted under full decode contention.
        plen = 3 * chunk + chunk // 2
        ttfts = []
        for i in range(int(os.environ.get("ARKS_BENCH_MIXED_TRIALS", "5"))):
            probe = Request(
                f"mixed{i}",
                [(7 + i + j) % cfg.vocab_size for j in range(plen)],
                SamplingParams(max_tokens=2, temperature=0.0,
                               ignore_eos=True))
            eng.add_request(probe)
            while True:
                out = probe.outputs.get(timeout=300)
                if out.ttft_s is not None:
                    ttfts.append(out.ttft_s * 1e3)
                if out.finished:
                    break
        for r in load:
            eng.abort(r.request_id)
        return float(np.percentile(ttfts, 50))
    finally:
        eng.stop()


def main() -> None:
    from arks_tpu.models import get_config
    from arks_tpu.models import quant
    from arks_tpu.models import transformer as tf

    model = os.environ.get("ARKS_BENCH_MODEL", "qwen2.5-7b")
    result: dict = {}
    # 192 beats 128 by ~9% and keeps ~2GB more HBM headroom than 256 on a
    # 16GB v5e (256 was only ~1% faster than 192 when measured).
    batch = int(os.environ.get("ARKS_BENCH_BATCH", "192"))
    cache_len = int(os.environ.get("ARKS_BENCH_CACHE_LEN", "1024"))
    # K sensitivity (b192, measured): 32 -> 6.44k, 64 -> 6.66k, 128 -> 6.78k
    # tok/s/chip.  32 stays the default: it matches a serving-realistic
    # scheduler granularity; bigger K trades admission latency for the
    # last ~5% by amortizing dispatch overhead further.
    steps = int(os.environ.get("ARKS_BENCH_STEPS", "32"))
    trials = int(os.environ.get("ARKS_BENCH_TRIALS", "3"))
    prompt_len = int(os.environ.get("ARKS_BENCH_PROMPT_LEN", "1024"))
    ttft_trials = int(os.environ.get("ARKS_BENCH_TTFT_TRIALS", "9"))
    kv_dtype = os.environ.get("ARKS_BENCH_KV_DTYPE", "int8")
    weight_dtype = os.environ.get("ARKS_BENCH_WEIGHT_DTYPE", "int8")
    kv_quant = kv_dtype == "int8"

    result["metric"] = (f"decode_throughput_{model}_b{batch}"
                        f"_w-{weight_dtype}_kv-{kv_dtype}")
    result["value"] = 0.0
    result["unit"] = "tok/s/chip"
    result["vs_baseline"] = 0.0

    # Backend availability gate: a flaky tunnel must produce a structured
    # JSON line — under the SAME metric name as a real run, so the failure
    # evidence lands next to the numbers it annotates — not a stack trace
    # and rc=1 (BENCH_r03 lost a round of evidence that way).  The probe is
    # PERSISTENT: it retries with capped exponential backoff for the whole
    # ARKS_BENCH_PROBE_DEADLINE_S window (default ~1h) — three rounds of
    # driver bench records were 0.0 purely because the old 3x180s loop gave
    # up before the tunnel came back.
    probe_t0 = time.monotonic()
    ok, err = probe_backend(
        timeout_s=float(os.environ.get("ARKS_BENCH_PROBE_TIMEOUT", "180")),
        deadline_s=float(os.environ.get("ARKS_BENCH_PROBE_DEADLINE_S",
                                        "3600")),
        backoff_s=float(os.environ.get("ARKS_BENCH_PROBE_BACKOFF", "10")),
        # Test hook: lets CI simulate an initially-unreachable backend
        # without touching a real tunnel.
        code=os.environ.get("ARKS_BENCH_PROBE_CODE"))
    result["probe_wait_s"] = round(time.monotonic() - probe_t0, 1)
    if not ok:
        result["error"] = f"jax backend unavailable after retries: {err}"
        print(json.dumps(result))
        return

    cfg = get_config(model)

    # Guided-decoding cold start: the host-side char-DFA + vocab-walk build
    # for JSON mode at this model's REAL vocab size — the latency the async
    # compile pipeline hides from the scheduler (it bounds added TTFT for
    # the first request per schema only; warm requests are a registry hit).
    # A byte-level vocab walks 1 byte per token where a merged-BPE vocab
    # walks ~word-length strings, so treat this as a floor, tracked across
    # BENCH rounds for regressions in the compile pipeline itself.
    try:
        from arks_tpu.engine.guides import GuideCompiler
        from arks_tpu.engine.tokenizer import ByteTokenizer
        gcomp = GuideCompiler(ByteTokenizer(), cfg.vocab_size, eos_ids=(0,))
        tg0 = time.perf_counter()
        gcomp.compile("json")
        result["guided_cold_start_s"] = round(time.perf_counter() - tg0, 3)
        del gcomp
    except Exception as e:
        result["guided_cold_start_error"] = f"{type(e).__name__}: {e}"

    n_chips = len(jax.devices())

    # ---- Raw-loop sections: fault-isolated so a failure here still leaves
    # a serving run + a parsable JSON line. ---------------------------------
    try:
        mesh = None
        if n_chips > 1:
            from arks_tpu.parallel.mesh import make_mesh
            mesh = make_mesh(tensor_parallel=n_chips)

        wbits = quant.weight_bits(weight_dtype)
        if wbits:
            params = quant.init_params_quantized(
                cfg, jax.random.PRNGKey(0), bits=wbits,
                shards=n_chips if n_chips > 1 else 1)
        else:
            params = tf.init_params(cfg, jax.random.PRNGKey(0))
        if mesh is not None:
            params = tf.shard_params(params, cfg, mesh)

        # -- TTFT: bucketed single-prompt prefill + first-token argmax
        # (UNLOADED — the loaded counterpart comes from the serving bench).
        def first_token(params, tokens, lengths):
            logits, ks, vs = tf.prefill(params, cfg, tokens, lengths, mesh)
            return jnp.argmax(logits, axis=-1).astype(jnp.int32)

        prefill_fn = jax.jit(first_token)
        toks = jnp.zeros((1, prompt_len), jnp.int32)
        lens = jnp.asarray([prompt_len], jnp.int32)
        np.asarray(prefill_fn(params, toks, lens))  # warmup/compile
        ttft_ms = []
        for _ in range(ttft_trials):
            t0 = time.perf_counter()
            np.asarray(prefill_fn(params, toks, lens))  # host fetch = barrier
            ttft_ms.append((time.perf_counter() - t0) * 1e3)
        ttft_p50 = float(np.percentile(ttft_ms, 50))
        result["ttft_p50_ms"] = round(ttft_p50, 1)
        result["ttft_prompt_len"] = prompt_len
        result["ttft_vs_target"] = round(TARGET_TTFT_MS / ttft_p50, 3)

        # -- Decode throughput: fused multi-step loop
        cache = tf.init_cache(cfg, num_slots=batch, max_len=cache_len,
                              quantized=kv_quant)

        def multi_step(params, cache, tokens, lengths):
            def body(carry, _):
                cache, tokens, lengths = carry
                logits, cache = tf.decode_step(
                    params, cfg, cache, tokens, lengths, mesh)
                nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
                return (cache, nxt, lengths + 1), nxt
            (cache, tokens, lengths), out = jax.lax.scan(
                body, (cache, tokens, lengths), None, length=steps)
            return cache, tokens, lengths, out

        fn = jax.jit(multi_step, donate_argnums=(1,))
        tokens = jnp.zeros((batch,), jnp.int32)
        # Mid-cache lengths: each decode step attends ~cache_len/2 of KV,
        # a representative steady-state working set.
        lengths = jnp.full((batch,), cache_len // 2, jnp.int32)

        cache, tokens, lengths, out = fn(params, cache, tokens, lengths)
        np.asarray(out[-1])  # warmup/compile

        best = float("inf")
        for _ in range(trials):
            lengths = jnp.full((batch,), cache_len // 2, jnp.int32)
            t0 = time.perf_counter()
            cache, tokens, lengths, out = fn(params, cache, tokens, lengths)
            np.asarray(out[-1])  # host fetch of ids = completion barrier
            best = min(best, time.perf_counter() - t0)

        tok_s_chip = batch * steps / best / max(n_chips, 1)
        result["value"] = round(tok_s_chip, 1)
        result["vs_baseline"] = round(tok_s_chip / BASELINE_TOK_S_CHIP, 3)
    except Exception as e:
        import traceback
        traceback.print_exc()
        result["raw_error"] = f"{type(e).__name__}: {e}"

    # TPU-side kernel parity rides every bench run: the Pallas decode path
    # must agree with the XLA oracle ON DEVICE, not just in CPU interpret
    # mode.  bf16 accumulation + (for int8) requantization of the new row
    # bound the tolerance.
    if jax.default_backend() == "tpu":  # interpret-mode parity is a unit test
        try:
            parity_diff = pallas_parity_check(kv_quant)
            result["pallas_parity_maxdiff"] = round(parity_diff, 5)
            result["pallas_parity_ok"] = \
                parity_diff < (0.075 if kv_quant else 0.05)
        except Exception as e:
            result["pallas_parity_error"] = f"{type(e).__name__}: {e}"

    # Kernel microbench rung: dense vs ragged mixed grid x int8/int4 KV x
    # default/tuned blocks on a sparse batch.  Fault-isolated;
    # ARKS_BENCH_KERNEL_MICRO=0 skips.
    if os.environ.get("ARKS_BENCH_KERNEL_MICRO", "1") != "0":
        try:
            result["kernel_microbench"] = measure_kernel_microbench()
        except Exception as e:
            import traceback
            traceback.print_exc()
            result["kernel_microbench_error"] = f"{type(e).__name__}: {e}"

    # Mixed-step TTFT under load: the decode+prefill-contention latency the
    # unified mixed dispatch (ARKS_MIXED_STEP) exists to bound.  Fault-
    # isolated like the raw loops; ARKS_BENCH_MIXED_TTFT=0 skips.
    if os.environ.get("ARKS_BENCH_MIXED_TTFT", "1") != "0":
        try:
            result["mixed_step_ttft_under_load_ms"] = round(
                measure_mixed_ttft_under_load(), 1)
        except Exception as e:
            import traceback
            traceback.print_exc()
            result["mixed_ttft_error"] = f"{type(e).__name__}: {e}"

    # Checkpoint line BEFORE the long serving phase: if the driver's
    # timeout kills this process mid-serving, the last printed JSON line
    # is still a parsed raw-loop result instead of nothing.  A completed
    # run prints the combined line after it, which then takes precedence
    # as the final line.
    print(json.dumps(result), flush=True)

    # Serving-path numbers (engine + OpenAI server + SSE under concurrent
    # load — bench_serving.py): the honest counterpart of the raw-loop
    # number above, and the number BASELINE.md actually specifies.
    # Raw-bench device buffers are dropped first so the serving engine's
    # params+cache fit HBM alongside nothing.
    if os.environ.get("ARKS_BENCH_SERVING", "1") != "0":
        import gc
        # Names are defined in this order; a mid-raw failure leaves a
        # prefix, and del stops at the first missing name — fine, the rest
        # were never created.
        try:
            del params, prefill_fn, cache, fn, tokens, lengths, out
        except NameError:
            pass
        gc.collect()
        try:
            from bench_serving import run_serving_bench
            result.update(run_serving_bench(model))
        except Exception as e:  # the raw-loop numbers must still print
            import traceback
            traceback.print_exc()
            result["serving_error"] = f"{type(e).__name__}: {e}"
        # Loaded TTFT vs the 200ms target rides the top-level pass/fail
        # fields next to the unloaded prefill number.
        lp50 = result.get("serving_ttft_p50_ms")
        if lp50:
            result["serving_ttft_vs_target"] = round(TARGET_TTFT_MS / lp50, 3)

    print(json.dumps(result))


if __name__ == "__main__":
    try:
        main()
    except BaseException as e:  # last-resort: ALWAYS emit a parsable line
        import traceback
        traceback.print_exc()
        print(json.dumps({
            "metric": "bench_failed", "value": 0.0, "unit": "tok/s/chip",
            "vs_baseline": 0.0, "error": f"{type(e).__name__}: {e}"}))
        raise SystemExit(0)
