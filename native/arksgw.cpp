// Native gateway data-plane hot paths for arks-tpu.
//
// The reference gateway is a compiled Go binary (pkg/gateway/); its two hot
// loops are the per-chunk SSE usage scan in HandleResponseBody
// (handle_response.go:113-182) and the fixed-window rate-limit counters
// (ratelimiter/redis_impl.go:47-168, backed by Redis).  This library is the
// native counterpart for the Python gateway: an in-process counter store
// with wall-clock-window expiry and an incremental SSE scanner that
// tolerates arbitrary chunk fragmentation.  Python binds via ctypes
// (arks_tpu/gateway/native.py); every entry point is C ABI.
//
// Build: native/Makefile -> build/libarksgw.so (g++ -O2 -fPIC -shared).

#include <algorithm>
#include <cctype>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <mutex>
#include <string>
#include <unordered_map>

namespace {

// ---------------------------------------------------------------------------
// Fixed-window counters
// ---------------------------------------------------------------------------

struct Counter {
  long long value;
  double expiry;
};

struct Store {
  std::mutex mu;
  std::unordered_map<std::string, Counter> map;
  size_t gc_at = 65536;  // next size at which to sweep expired entries
};

constexpr size_t kGcThreshold = 65536;

// ---------------------------------------------------------------------------
// SSE usage scanner
// ---------------------------------------------------------------------------

struct Scanner {
  std::string buf;  // unterminated frame tail across feeds
  long long prompt = -1, completion = -1, total = -1;
  bool has_usage = false;
  bool done = false;  // saw the [DONE] sentinel
};

bool parse_ll_after(const std::string& s, const char* key, long long* out) {
  size_t pos = s.find(key);
  if (pos == std::string::npos) return false;
  pos += std::strlen(key);
  while (pos < s.size() &&
         (s[pos] == ' ' || s[pos] == '\t' || s[pos] == ':'))
    pos++;
  if (pos >= s.size() ||
      !(std::isdigit(static_cast<unsigned char>(s[pos])) || s[pos] == '-'))
    return false;
  *out = std::strtoll(s.c_str() + pos, nullptr, 10);
  return true;
}

void handle_frame(Scanner* sc, const std::string& frame) {
  size_t start = 0;
  while (start < frame.size()) {
    size_t end = frame.find('\n', start);
    std::string line = frame.substr(
        start, end == std::string::npos ? std::string::npos : end - start);
    start = end == std::string::npos ? frame.size() : end + 1;
    if (!line.empty() && line.back() == '\r') line.pop_back();
    if (line.rfind("data:", 0) != 0) continue;
    std::string payload = line.substr(5);
    size_t b = payload.find_first_not_of(" \t");
    payload = b == std::string::npos ? "" : payload.substr(b);
    if (payload == "[DONE]") {
      sc->done = true;
      continue;
    }
    // Usage must be a JSON object, not the null most chunks carry.
    size_t up = payload.find("\"usage\"");
    if (up == std::string::npos) continue;
    size_t q = payload.find_first_not_of(" \t:", up + 7);
    if (q == std::string::npos || payload[q] != '{') continue;
    // Bound the scan to the usage object itself (balanced braces) and
    // REPLACE all three fields per frame — later usage frames must fully
    // supersede earlier ones (e.g. per-chunk continuous usage stats), the
    // same whole-dict-replacement semantics as the Python fallback.
    int depth = 0;
    size_t uend = q;
    for (; uend < payload.size(); uend++) {
      if (payload[uend] == '{') depth++;
      else if (payload[uend] == '}' && --depth == 0) { uend++; break; }
    }
    std::string usage = payload.substr(q, uend - q);
    long long p, c, t;
    bool hp = parse_ll_after(usage, "\"prompt_tokens\"", &p);
    bool hc = parse_ll_after(usage, "\"completion_tokens\"", &c);
    bool ht = parse_ll_after(usage, "\"total_tokens\"", &t);
    // Replace all three fields per frame — later usage frames fully
    // supersede earlier ones — but ONLY when the frame carries at least one
    // numeric counter: an empty or non-numeric usage object must not clear
    // previously captured usage (PyUsageScanner applies the same rule).
    if (!(hp || hc || ht)) continue;
    sc->prompt = hp ? p : -1;
    sc->completion = hc ? c : -1;
    sc->total = ht ? t : -1;
    sc->has_usage = true;
  }
}

}  // namespace

extern "C" {

// ---- counters -------------------------------------------------------------

void* arks_store_new() { return new Store(); }

void arks_store_free(void* h) { delete static_cast<Store*>(h); }

long long arks_store_get(void* h, const char* key, double now) {
  Store* s = static_cast<Store*>(h);
  std::lock_guard<std::mutex> g(s->mu);
  auto it = s->map.find(key);
  if (it == s->map.end() || it->second.expiry <= now) return 0;
  return it->second.value;
}

long long arks_store_incr(void* h, const char* key, long long amount,
                          double ttl_s, double now) {
  Store* s = static_cast<Store*>(h);
  std::lock_guard<std::mutex> g(s->mu);
  if (s->map.size() > s->gc_at) {
    // Amortized sweep: if most entries are live (long windows), the next
    // sweep waits for the map to double rather than re-scanning every
    // increment under the mutex.
    for (auto it = s->map.begin(); it != s->map.end();) {
      it = it->second.expiry <= now ? s->map.erase(it) : std::next(it);
    }
    s->gc_at = std::max(kGcThreshold, s->map.size() * 2);
  }
  Counter& c = s->map[key];
  if (c.expiry <= now) {
    c.value = 0;
    c.expiry = now + ttl_s;
  }
  c.value += amount;
  return c.value;
}

long long arks_store_size(void* h) {
  Store* s = static_cast<Store*>(h);
  std::lock_guard<std::mutex> g(s->mu);
  return static_cast<long long>(s->map.size());
}

// ---- SSE scanner ----------------------------------------------------------

void* arks_sse_new() { return new Scanner(); }

void arks_sse_free(void* h) { delete static_cast<Scanner*>(h); }

void arks_sse_feed(void* h, const char* data, size_t len) {
  Scanner* sc = static_cast<Scanner*>(h);
  sc->buf.append(data, len);
  for (;;) {
    // Frames end at a blank line: "\n\n" or "\r\n\r\n", whichever first.
    size_t a = sc->buf.find("\n\n");
    size_t b = sc->buf.find("\r\n\r\n");
    size_t pos, sep;
    if (a == std::string::npos && b == std::string::npos) break;
    if (b != std::string::npos && (a == std::string::npos || b < a)) {
      pos = b;
      sep = 4;
    } else {
      pos = a;
      sep = 2;
    }
    handle_frame(sc, sc->buf.substr(0, pos));
    sc->buf.erase(0, pos + sep);
  }
}

// Returns 1 when a usage object was seen; fills the three counters
// (absent fields are -1).
int arks_sse_result(void* h, long long* prompt, long long* completion,
                    long long* total) {
  Scanner* sc = static_cast<Scanner*>(h);
  *prompt = sc->prompt;
  *completion = sc->completion;
  *total = sc->total;
  return sc->has_usage ? 1 : 0;
}

int arks_sse_done(void* h) { return static_cast<Scanner*>(h)->done ? 1 : 0; }

}  // extern "C"
