"""Gateway data-plane load test: N-hundred concurrent SSE streams.

The reference fronts with Envoy (a C++ event loop); this gateway is a
threaded Python proxy with a native usage scanner.  This harness measures
what that is actually good for: aggregate streamed frames/s and per-frame
relay overhead at high concurrency, gateway vs DIRECT-to-backend, using a
synthetic SSE backend so the numbers isolate the PROXY (no model time).

Usage: python tools/bench_gateway.py [--streams 200] [--frames 50]
Prints one JSON line; paste results into docs/monitoring.md.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def make_backend(frames: int, frame_interval_s: float, body_bytes: int):
    """Synthetic OpenAI-ish SSE backend: ``frames`` data frames per
    request, then a usage frame and [DONE]."""
    filler = "x" * body_bytes

    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, fmt, *args):
            pass

        def do_POST(self):
            length = int(self.headers.get("Content-Length", 0))
            self.rfile.read(length)
            self.send_response(200)
            self.send_header("Content-Type", "text/event-stream")
            self.send_header("Transfer-Encoding", "chunked")
            self.end_headers()

            def frame(obj):
                data = b"data: " + json.dumps(obj).encode() + b"\n\n"
                self.wfile.write(f"{len(data):x}\r\n".encode() + data + b"\r\n")
                self.wfile.flush()

            for i in range(frames):
                frame({"choices": [{"delta": {"content": filler}}]})
                if frame_interval_s:
                    time.sleep(frame_interval_s)
            frame({"choices": [],
                   "usage": {"prompt_tokens": 7, "completion_tokens": frames,
                             "total_tokens": 7 + frames}})
            data = b"data: [DONE]\n\n"
            self.wfile.write(f"{len(data):x}\r\n".encode() + data + b"\r\n")
            self.wfile.write(b"0\r\n\r\n")
            self.wfile.flush()

    class Server(ThreadingHTTPServer):
        request_queue_size = 512
        daemon_threads = True

    srv = Server(("127.0.0.1", 0), Handler)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    return srv


def run_load(url: str, path: str, streams: int, rounds: int,
             headers: dict | None = None) -> dict:
    import http.client

    host, _, port = url.partition(":")
    lock = threading.Lock()
    stats = {"frames": 0, "streams": 0, "errors": 0, "ttfb": []}
    body = json.dumps({"model": "lt", "stream": True,
                       "stream_options": {"include_usage": True},
                       "messages": [{"role": "user", "content": "load"}],
                       }).encode()

    def worker():
        conn = http.client.HTTPConnection(host, int(port), timeout=120)
        for _ in range(rounds):
            try:
                t0 = time.monotonic()
                conn.request("POST", path, body=body, headers={
                    "Content-Type": "application/json", **(headers or {})})
                resp = conn.getresponse()
                first = None
                n = 0
                while True:
                    chunk = resp.read1(65536)
                    if not chunk:
                        break
                    if first is None:
                        first = time.monotonic() - t0
                    n += chunk.count(b"data: ")
                with lock:
                    stats["frames"] += n
                    stats["streams"] += 1
                    if first is not None:
                        stats["ttfb"].append(first)
            except Exception:
                with lock:
                    stats["errors"] += 1
                conn.close()
                conn = http.client.HTTPConnection(host, int(port), timeout=120)
        conn.close()

    threads = [threading.Thread(target=worker) for _ in range(streams)]
    t0 = time.monotonic()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.monotonic() - t0
    ttfb = sorted(stats["ttfb"])
    return {
        "streams_done": stats["streams"], "errors": stats["errors"],
        "frames_per_s": round(stats["frames"] / wall, 1),
        "streams_per_s": round(stats["streams"] / wall, 1),
        "ttfb_p50_ms": round(ttfb[len(ttfb) // 2] * 1e3, 1) if ttfb else None,
        "ttfb_p99_ms": round(ttfb[int(len(ttfb) * 0.99)] * 1e3, 1)
        if ttfb else None,
        "wall_s": round(wall, 1),
    }


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--streams", type=int, default=200)
    ap.add_argument("--rounds", type=int, default=3)
    ap.add_argument("--frames", type=int, default=50)
    ap.add_argument("--frame-interval-ms", type=float, default=0.0,
                    help="per-frame backend pacing (0 = as fast as possible "
                         "-> measures the relay ceiling)")
    ap.add_argument("--frame-bytes", type=int, default=64)
    args = ap.parse_args()

    from arks_tpu.control import resources as res
    from arks_tpu.control.store import Store
    from arks_tpu.gateway.server import Gateway

    backend = make_backend(args.frames, args.frame_interval_ms / 1e3,
                           args.frame_bytes)
    baddr = f"127.0.0.1:{backend.server_address[1]}"

    store = Store()
    ep = res.Endpoint(name="lt", spec={"defaultWeight": 1})
    ep.status["routes"] = [{"backend": {"addresses": [baddr]}, "weight": 1}]
    store.create(ep)
    store.create(res.Token(name="lt-user", spec={
        "token": "sk-lt",
        "qos": [{"endpoint": {"name": "lt"},
                 "rateLimits": [{"type": "rpm", "value": 10_000_000}]}]}))
    gw = Gateway(store, host="127.0.0.1", port=0)
    gw.start(background=True)

    direct = run_load(baddr, "/v1/chat/completions", args.streams, args.rounds)
    via_gw = run_load(f"127.0.0.1:{gw.port}", "/v1/chat/completions",
                      args.streams, args.rounds,
                      headers={"Authorization": "Bearer sk-lt"})
    gw.stop()
    overhead = (1 - via_gw["frames_per_s"] / direct["frames_per_s"]
                if direct["frames_per_s"] else None)
    print(json.dumps({
        "config": {"streams": args.streams, "rounds": args.rounds,
                   "frames": args.frames,
                   "frame_interval_ms": args.frame_interval_ms,
                   "frame_bytes": args.frame_bytes},
        "direct": direct,
        "gateway": via_gw,
        "gateway_throughput_overhead": round(overhead, 3)
        if overhead is not None else None,
    }))


if __name__ == "__main__":
    main()
