"""Serving-path knob sweep: find the best scheduler configuration on-chip.

Runs the REAL serving benchmark (bench_serving.run_serving_bench — engine +
OpenAI server + SSE under concurrent load) once per configuration, each in
a FRESH subprocess (engine/env state cannot leak between configs), and
prints one JSON line per run plus a ranked summary.  The knobs swept are
exactly the env-tunable scheduler levers:

- ARKS_BENCH_STEPS       (decode steps per dispatch, K)
- ARKS_ADMIT_BATCH_SIZES (fused-admission fill ladder)
- ARKS_OVERLAP_DECODE    (decode/admission overlap)

Usage:
  timeout 3600 python tools/bench_sweep.py               # default grid
  SWEEP_GRID='[{"ARKS_BENCH_STEPS":"64"}]' python tools/bench_sweep.py

Each config costs ~2-4 min on the chip (priming + warmup + window); the
default grid is 6 configs.  Meaningful only on real TPU.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

DEFAULT_GRID = [
    {},  # production defaults — the baseline the others must beat
    {"ARKS_BENCH_STEPS": "64"},
    {"ARKS_ADMIT_BATCH_SIZES": "16,8,4,2,1"},
    {"ARKS_BENCH_STEPS": "64", "ARKS_ADMIT_BATCH_SIZES": "16,8,4,2,1"},
    {"ARKS_OVERLAP_DECODE": "0"},
    {"ARKS_BENCH_STEPS": "16"},
]


SWEPT_KEYS = ("ARKS_BENCH_STEPS", "ARKS_ADMIT_BATCH_SIZES",
              "ARKS_OVERLAP_DECODE")


def run_config(overrides: dict[str, str], timeout_s: float) -> dict:
    env = dict(os.environ)
    # The swept knobs start CLEAN: a pre-exported ARKS_* from earlier
    # experimentation must not contaminate the "defaults" baseline (the
    # config label must describe what actually ran).
    for key in SWEPT_KEYS:
        env.pop(key, None)
    # Sweeps rank configs by saturation throughput; the moderate-load TTFT
    # phase (~40s/config) belongs to the final bench, not the grid.
    env["ARKS_BENCH_SERVE_MODERATE"] = "0"
    env.update(overrides)
    code = ("import json\n"
            "from bench_serving import run_serving_bench\n"
            "print('SWEEP_RESULT ' + json.dumps(run_serving_bench()))\n")
    t0 = time.monotonic()
    try:
        r = subprocess.run([sys.executable, "-c", code], cwd=REPO, env=env,
                           capture_output=True, text=True,
                           timeout=timeout_s)
    except subprocess.TimeoutExpired:
        return {"config": overrides, "error": f"timeout {timeout_s:.0f}s"}
    for line in reversed(r.stdout.strip().splitlines()):
        if line.startswith("SWEEP_RESULT "):
            out = json.loads(line[len("SWEEP_RESULT "):])
            out["config"] = overrides
            out["wall_s"] = round(time.monotonic() - t0, 1)
            return out
    tail = (r.stderr or r.stdout).strip().splitlines()[-1:]
    return {"config": overrides,
            "error": f"rc={r.returncode}: {tail[0][-300:] if tail else ''}"}


def main() -> None:
    grid = json.loads(os.environ.get("SWEEP_GRID", "null")) or DEFAULT_GRID
    per_run_timeout = float(os.environ.get("SWEEP_RUN_TIMEOUT", "600"))
    results = []
    for i, overrides in enumerate(grid):
        print(f"# sweep {i + 1}/{len(grid)}: {overrides or 'defaults'}",
              file=sys.stderr, flush=True)
        res = run_config(overrides, per_run_timeout)
        results.append(res)
        print(json.dumps(res), flush=True)
    ranked = sorted((r for r in results if "serving_tok_s_chip" in r),
                    key=lambda r: -r["serving_tok_s_chip"])
    print(json.dumps({
        "metric": "serving_sweep_best",
        "ranking": [{"config": r["config"],
                     "serving_tok_s_chip": r["serving_tok_s_chip"],
                     "serving_ttft_p50_ms": r.get("serving_ttft_p50_ms")}
                    for r in ranked],
        "errors": [r for r in results if "error" in r],
    }), flush=True)


if __name__ == "__main__":
    main()
