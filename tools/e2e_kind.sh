#!/usr/bin/env bash
# Real-cluster e2e on Kind — the executable form of docs/runbook.md §1-§5,
# mirroring the reference's Kind suite (test/e2e/e2e_test.go:45-270):
# deploy CRDs + operator, assert the controller runs, serve the quickstart,
# complete a request through the gateway (auth positive AND negative),
# scrape TokenReview-authenticated operator metrics, kill the leader and
# assert standby failover, tear down.
#
# Usage:   tools/e2e_kind.sh
# Env:     CLUSTER=arks-e2e      kind cluster name
#          EXISTING_CLUSTER=1    skip kind create/delete (use current ctx)
#          KEEP=1                keep the cluster + workloads on success
#          SKIP_BUILD=1          image already present in the cluster
set -euo pipefail

CLUSTER="${CLUSTER:-arks-e2e}"
IMG=arks-tpu/engine:latest
ROOT="$(cd "$(dirname "$0")/.." && pwd)"
cd "$ROOT"

need() { command -v "$1" >/dev/null || { echo "SKIP: $1 not installed" >&2; exit 3; }; }
need kind; need kubectl; need docker; need curl

say() { echo "=== $*" >&2; }

cleanup() {
  code=$?
  if [ "${KEEP:-0}" != 1 ] && [ "${EXISTING_CLUSTER:-0}" != 1 ]; then
    kind delete cluster --name "$CLUSTER" >/dev/null 2>&1 || true
  fi
  pkill -f "kubectl.*port-forward.*arks" 2>/dev/null || true
  exit $code
}
trap cleanup EXIT

if [ "${EXISTING_CLUSTER:-0}" != 1 ]; then
  say "creating kind cluster $CLUSTER"
  kind create cluster --name "$CLUSTER" --wait 120s
fi

if [ "${SKIP_BUILD:-0}" != 1 ]; then
  say "building + loading $IMG"
  docker build -t "$IMG" -f dockerfiles/Dockerfile .
  kind load docker-image "$IMG" --name "$CLUSTER"
fi

say "installing CRDs + operator (runbook §1)"
kubectl apply -f deploy/crds.yaml
kubectl apply -f deploy/operator.yaml
kubectl -n arks-system rollout status deploy/arks-operator --timeout=180s

say "asserting exactly one Ready replica (leader-only readiness)"
ready_count() {
  kubectl -n arks-system get pods -l app=arks-operator \
    -o jsonpath='{range .items[*]}{.status.containerStatuses[0].ready}{"\n"}{end}' \
    | grep -c true || true
}
for i in $(seq 1 60); do
  [ "$(ready_count)" = 1 ] && break
  sleep 2
done
[ "$(ready_count)" = 1 ] || { echo "FAIL: want exactly 1 Ready operator replica, got $(ready_count)" >&2; exit 1; }

say "serving the quickstart (runbook §2)"
kubectl apply -f examples/quickstart/quickstart.yaml
for i in $(seq 1 90); do
  phase=$(kubectl get arksapplication qwen2.5-app -o jsonpath='{.status.phase}' 2>/dev/null || true)
  [ "$phase" = Running ] && break
  sleep 2
done
[ "${phase:-}" = Running ] || { echo "FAIL: quickstart phase=$phase (want Running)" >&2; kubectl describe arksapplication qwen2.5-app >&2 || true; exit 1; }

say "completion through the gateway (runbook §3)"
kubectl -n arks-system port-forward svc/arks-operator-gateway 18081:8081 >/dev/null 2>&1 &
PF=$!
sleep 3
body='{"model": "qwen2.5", "messages": [{"role": "user", "content": "hi"}], "max_tokens": 8}'
resp=$(curl -sf localhost:18081/v1/chat/completions \
  -H 'Authorization: Bearer sk-quickstart' -H 'Content-Type: application/json' \
  -d "$body")
echo "$resp" | grep -q '"usage"' || { echo "FAIL: no usage in completion: $resp" >&2; exit 1; }
code=$(curl -s -o /dev/null -w '%{http_code}' localhost:18081/v1/chat/completions \
  -H 'Content-Type: application/json' -d "$body")
[ "$code" = 401 ] || { echo "FAIL: unauthenticated completion got $code (want 401)" >&2; exit 1; }
kill $PF 2>/dev/null || true

say "TokenReview-authenticated metrics scrape (runbook §4)"
kubectl -n arks-system port-forward deploy/arks-operator 18082:8082 >/dev/null 2>&1 &
PF=$!
sleep 3
tok=$(kubectl -n arks-system create token arks-operator)
mcode=$(curl -s -o /tmp/arks_e2e_metrics -w '%{http_code}' \
  -H "Authorization: Bearer $tok" localhost:18082/metrics)
[ "$mcode" = 200 ] || { echo "FAIL: authed metrics scrape got $mcode" >&2; exit 1; }
ucode=$(curl -s -o /dev/null -w '%{http_code}' localhost:18082/metrics)
case "$ucode" in 401|403) ;; *) echo "FAIL: unauthed metrics got $ucode (want 401/403)" >&2; exit 1;; esac
kill $PF 2>/dev/null || true

say "leader failover: delete the Ready pod, standby must take over"
leader=$(kubectl -n arks-system get pods -l app=arks-operator \
  -o jsonpath='{range .items[*]}{.metadata.name}={.status.containerStatuses[0].ready}{"\n"}{end}' \
  | awk -F= '$2=="true"{print $1; exit}')
[ -n "$leader" ] || { echo "FAIL: no Ready operator pod found" >&2; exit 1; }
kubectl -n arks-system delete pod "$leader" --wait=false
for i in $(seq 1 90); do
  now=$(kubectl -n arks-system get pods -l app=arks-operator \
    -o jsonpath='{range .items[*]}{.metadata.name}={.status.containerStatuses[0].ready}{"\n"}{end}' \
    | awk -F= '$2=="true"{print $1; exit}')
  if [ -n "$now" ] && [ "$now" != "$leader" ]; then break; fi
  now=""
  sleep 2
done
[ -n "$now" ] || { echo "FAIL: no standby became Ready after leader deletion" >&2; exit 1; }
say "failover OK: $leader -> $now"

if [ "${KEEP:-0}" != 1 ]; then
  say "teardown (runbook §5)"
  kubectl delete -f examples/quickstart/quickstart.yaml --timeout=120s
  kubectl delete -f deploy/operator.yaml -f deploy/crds.yaml --timeout=120s
fi

say "PASS"
