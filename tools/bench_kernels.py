"""Kernel microbench: paged vs slot-contiguous decode attention on TPU.

Times a fused L-layer update+attend loop (the decode dispatch's attention
cost) for both cache designs at production shapes.  Gate for the paged
rollout: paged must be within a few percent of contiguous, or the engine
default stays slot-contiguous.

Usage: timeout 600 python tools/bench_kernels.py  (runs on the default
backend; meaningful numbers only on real TPU).
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np


def main() -> None:
    L = int(os.environ.get("KB_LAYERS", "28"))
    B = int(os.environ.get("KB_BATCH", "192"))
    Hkv = int(os.environ.get("KB_HKV", "4"))
    G = int(os.environ.get("KB_G", "7"))
    S = int(os.environ.get("KB_S", "1024"))
    D = int(os.environ.get("KB_D", "128"))
    P = int(os.environ.get("KB_PAGE", "256"))
    K = int(os.environ.get("KB_STEPS", "32"))
    quant = os.environ.get("KB_QUANT", "1") == "1"
    trials = int(os.environ.get("KB_TRIALS", "5"))
    interpret = jax.default_backend() != "tpu"

    from arks_tpu.ops.pallas_attention import (
        kv_cache_update, kv_cache_update_quant, ragged_decode_attention)
    from arks_tpu.ops.paged_attention import (
        paged_decode_attention, paged_kv_update, paged_kv_update_quant)

    key = jax.random.PRNGKey(0)
    ks = jax.random.split(key, 8)
    q = jax.random.normal(ks[0], (B, Hkv, G, D), jnp.bfloat16)
    kn = jax.random.normal(ks[1], (B, Hkv, D), jnp.bfloat16)
    vn = jax.random.normal(ks[2], (B, Hkv, D), jnp.bfloat16)
    lengths = (jnp.arange(B, dtype=jnp.int32) * 37) % (S - K - 1) + 1
    N = B * (S // P)
    max_pages = S // P
    # Worst-case scatter: pages striped so adjacent slots' pages are far
    # apart in the pool.
    tables = ((jnp.arange(B)[:, None] + jnp.arange(max_pages)[None] * B)
              % N).astype(jnp.int32)

    if quant:
        kc = jnp.zeros((L, B, Hkv, S, D), jnp.int8)
        vc = jnp.zeros((L, B, Hkv, S, D), jnp.int8)
        kcs = jnp.zeros((L, B, Hkv, S), jnp.float32)
        vcs = jnp.zeros((L, B, Hkv, S), jnp.float32)
        kp = jnp.zeros((L, N, Hkv, P, D), jnp.int8)
        vp = jnp.zeros((L, N, Hkv, P, D), jnp.int8)
        kps = jnp.zeros((L, N, Hkv, P), jnp.float32)
        vps = jnp.zeros((L, N, Hkv, P), jnp.float32)
    else:
        kc = jnp.zeros((L, B, Hkv, S, D), jnp.bfloat16)
        vc = jnp.zeros((L, B, Hkv, S, D), jnp.bfloat16)
        kcs = vcs = None
        kp = jnp.zeros((L, N, Hkv, P, D), jnp.bfloat16)
        vp = jnp.zeros((L, N, Hkv, P, D), jnp.bfloat16)
        kps = vps = None

    def contiguous_step(kc, vc, kcs, vcs, lengths):
        def layer_body(carry, lyr):
            kc, vc, kcs, vcs, acc = carry
            if quant:
                kc, vc, kcs, vcs = kv_cache_update_quant(
                    kc, vc, kcs, vcs, kn, vn, lengths, lyr,
                    interpret=interpret)
            else:
                kc, vc = kv_cache_update(kc, vc, kn, vn, lengths, lyr,
                                         interpret=interpret)
            out = ragged_decode_attention(
                q, kc, vc, lengths + 1, lyr, k_scale=kcs, v_scale=vcs,
                block_b=int(os.environ.get("ARKS_ATTN_BLOCK_B", "16")),
                interpret=interpret)
            return (kc, vc, kcs, vcs, acc + out.astype(jnp.float32)), None

        def step_body(carry, _):
            kc, vc, kcs, vcs, lengths = carry
            (kc, vc, kcs, vcs, acc), _ = jax.lax.scan(
                layer_body, (kc, vc, kcs, vcs,
                             jnp.zeros((B, Hkv, G, D), jnp.float32)),
                jnp.arange(L))
            return (kc, vc, kcs, vcs, lengths + 1), acc[0, 0, 0, 0]

        (kc, vc, kcs, vcs, lengths), outs = jax.lax.scan(
            step_body, (kc, vc, kcs, vcs, lengths), None, length=K)
        return kc, vc, kcs, vcs, outs

    def paged_step(kp, vp, kps, vps, lengths):
        def layer_body(carry, lyr):
            kp, vp, kps, vps, acc = carry
            if quant:
                kp, vp, kps, vps = paged_kv_update_quant(
                    kp, vp, kps, vps, kn, vn, lengths, tables, lyr,
                    interpret=interpret)
            else:
                kp, vp = paged_kv_update(kp, vp, kn, vn, lengths, tables,
                                         lyr, interpret=interpret)
            out = paged_decode_attention(q, kp, vp, tables, lengths + 1, lyr,
                                         k_scale=kps, v_scale=vps,
                                         interpret=interpret)
            return (kp, vp, kps, vps, acc + out.astype(jnp.float32)), None

        def step_body(carry, _):
            kp, vp, kps, vps, lengths = carry
            (kp, vp, kps, vps, acc), _ = jax.lax.scan(
                layer_body, (kp, vp, kps, vps,
                             jnp.zeros((B, Hkv, G, D), jnp.float32)),
                jnp.arange(L))
            return (kp, vp, kps, vps, lengths + 1), acc[0, 0, 0, 0]

        (kp, vp, kps, vps, lengths), outs = jax.lax.scan(
            step_body, (kp, vp, kps, vps, lengths), None, length=K)
        return kp, vp, kps, vps, outs

    results = {}
    for name, fn, args in (
        ("contiguous", jax.jit(contiguous_step, donate_argnums=(0, 1, 2, 3)),
         (kc, vc, kcs, vcs, lengths)),
        ("paged", jax.jit(paged_step, donate_argnums=(0, 1, 2, 3)),
         (kp, vp, kps, vps, lengths)),
    ):
        if not quant:
            args = (args[0], args[1], None, None, args[4])
        *state, outs = fn(*args)
        np.asarray(outs[-1])  # compile + warmup
        best = float("inf")
        for _ in range(trials):
            t0 = time.perf_counter()
            *state, outs = fn(*state, lengths)
            np.asarray(outs[-1])
            best = min(best, time.perf_counter() - t0)
        results[name] = best
        del state

    ratio = results["paged"] / results["contiguous"]
    print(json.dumps({
        "contiguous_ms_per_Kstep": round(results["contiguous"] * 1e3, 2),
        "paged_ms_per_Kstep": round(results["paged"] * 1e3, 2),
        "paged_vs_contiguous": round(ratio, 3),
        "shape": f"L{L} B{B} Hkv{Hkv} G{G} S{S} D{D} P{P} K{K} quant={quant}",
    }))


if __name__ == "__main__":
    main()
