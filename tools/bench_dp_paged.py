"""Quantify the dp/paged exclusion (engine.py:_resolve_kv_layout).

The paged KV pool has no batch dim to shard over a ``data`` mesh axis, so
dp-meshed engines fall back to the slot layout — trading away on-device
prefix sharing and page-granular HBM.  The recommended alternative is
REPLICA GROUPS (independent engines behind weighted routes), each running
paged.  This tool measures both sides per chip:

  A. one engine meshed dp=DP over DP devices, slot layout (the excluded
     configuration), throughput / DP chips;
  B. one single-device engine on the paged layout (a replica group member
     — replica scaling is linear by construction, no cross-replica
     collectives), throughput / 1 chip.

Run on TPU for real numbers (paged interpret-mode kernels make CPU
figures mechanics-only):

  python tools/bench_dp_paged.py                     # chip defaults
  XLA_FLAGS=--xla_force_host_platform_device_count=2 \
  JAX_PLATFORMS=cpu ARKS_DPBENCH_MODEL=tiny \
  ARKS_DPBENCH_REQUESTS=8 ARKS_DPBENCH_MAX_TOKENS=16 \
  python tools/bench_dp_paged.py                     # CPU mechanics

Prints ONE JSON line.
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _run_engine(model: str, *, data_parallel: int, kv_layout: str,
                num_slots: int, cache_len: int, steps: int,
                requests: int, prompt_len: int, max_tokens: int) -> float:
    """Tokens/second over `requests` greedy requests, drained together."""
    import numpy as np

    from arks_tpu.engine import EngineConfig, InferenceEngine
    from arks_tpu.engine.tokenizer import ByteTokenizer
    from arks_tpu.engine.types import Request, SamplingParams
    from arks_tpu.models import get_config

    cfg = get_config(model)
    ecfg = EngineConfig(
        model=model, num_slots=num_slots, max_cache_len=cache_len,
        steps_per_dispatch=steps, kv_layout=kv_layout,
        data_parallel=data_parallel,
        weight_dtype=os.environ.get("ARKS_DPBENCH_WEIGHT_DTYPE", "bf16"),
        prefill_buckets=(max(prompt_len, 8),))
    eng = InferenceEngine(cfg, ecfg, ByteTokenizer())
    eng.start()
    rng = np.random.default_rng(0)
    try:
        reqs = []
        params = SamplingParams(max_tokens=max_tokens, temperature=0.0,
                                ignore_eos=True)
        # Warmup: compile every program before the measured window.
        w = Request(request_id="warm",
                    prompt_ids=[int(x) for x in
                                rng.integers(3, 200, prompt_len)],
                    params=SamplingParams(max_tokens=steps + 1,
                                          temperature=0.0, ignore_eos=True))
        eng.add_request(w)
        while True:
            if w.outputs.get(timeout=600).finished:
                break
        t0 = time.monotonic()
        for i in range(requests):
            r = Request(request_id=f"r{i}",
                        prompt_ids=[int(x) for x in
                                    rng.integers(3, 200, prompt_len)],
                        params=params)
            eng.add_request(r)
            reqs.append(r)
        total = 0
        for r in reqs:
            while True:
                out = r.outputs.get(timeout=1200)
                total += len(out.token_ids)
                if out.finished:
                    break
        dt = time.monotonic() - t0
        return total / dt
    finally:
        eng.stop()


def main() -> None:
    env = os.environ.get
    if env("JAX_PLATFORMS"):
        import jax
        jax.config.update("jax_platforms", env("JAX_PLATFORMS"))
    import jax
    devs = jax.devices()
    on_tpu = jax.default_backend() == "tpu"
    dp = int(env("ARKS_DPBENCH_DP", "2"))
    if len(devs) < dp:
        print(json.dumps({"error": f"need {dp} devices, have {len(devs)}"}))
        return
    model = env("ARKS_DPBENCH_MODEL", "qwen2.5-7b" if on_tpu else "tiny")
    requests = int(env("ARKS_DPBENCH_REQUESTS", "64" if on_tpu else "8"))
    num_slots = int(env("ARKS_DPBENCH_SLOTS", "32" if on_tpu else "4"))
    cache_len = int(env("ARKS_DPBENCH_CACHE_LEN", "1024" if on_tpu else "64"))
    prompt_len = int(env("ARKS_DPBENCH_PROMPT_LEN", "128" if on_tpu else "8"))
    max_tokens = int(env("ARKS_DPBENCH_MAX_TOKENS", "128" if on_tpu else "8"))
    steps = int(env("ARKS_DPBENCH_STEPS", "8" if on_tpu else "2"))

    common = dict(num_slots=num_slots, cache_len=cache_len, steps=steps,
                  prompt_len=prompt_len, max_tokens=max_tokens)
    # A: the excluded config — dp mesh forces the slot layout.
    a = _run_engine(model, data_parallel=dp, kv_layout="slot",
                    requests=requests, **common)
    # B: a replica-group member — single device, paged (the production
    # default on TPU; CPU runs it in interpret mode, mechanics only).
    b_layout = "paged" if on_tpu else env("ARKS_DPBENCH_B_LAYOUT", "slot")
    b = _run_engine(model, data_parallel=1, kv_layout=b_layout,
                    requests=requests // dp, **common)
    a_chip, b_chip = a / dp, b
    print(json.dumps({
        "backend": jax.default_backend(),
        "model": model,
        "dp": dp,
        "dp_slot_tok_s_chip": round(a_chip, 1),
        "replica_tok_s_chip": round(b_chip, 1),
        "replica_layout": b_layout,
        "dp_penalty_pct": round((1 - a_chip / b_chip) * 100, 1) if b_chip
        else None,
        "mechanics_only": not on_tpu,
    }))


if __name__ == "__main__":
    sys.exit(main())
