"""One-shot on-chip measurement for the pending kernel defaults.

Round-3 shipped three kernel paths without hardware numbers (the tunnel
died); this script captures ALL of them in one run so a single command
settles the defaults when the chip is back:

1. paged vs slot-contiguous decode attention at production shapes
   (delegates to tools/bench_kernels.py — the existing gate).
2. lane-padded d<128 decode (qwen2.5-0.5b shapes, head_dim 64 stored at
   128 so the Pallas kernels apply) vs the unpadded XLA fallback those
   models would otherwise ride — decides ARKS_PAD_HEAD_DIM's default.
3. MoE block-sparse grouped-matmul Pallas kernel vs jax.lax.ragged_dot at
   Mixtral-8x7B prefill shapes — decides ARKS_MOE_KERNEL's default.

Prints one JSON line per section.  Usage:
  timeout 1200 python tools/bench_defaults.py
Meaningful numbers only on real TPU (CPU runs interpret-mode kernels).
"""

from __future__ import annotations

import functools
import json
import os
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np


def _best(fn, trials: int) -> float:
    out = fn()
    jax.block_until_ready(out)  # compile
    best = float("inf")
    for _ in range(trials):
        t0 = time.perf_counter()
        out = fn()
        np.asarray(jax.tree_util.tree_leaves(out)[0][..., :1])  # host barrier
        best = min(best, time.perf_counter() - t0)
    return best


def bench_paged_vs_slot() -> None:
    """Section 1: forward to the existing microbench (one JSON line)."""
    r = subprocess.run(
        [sys.executable, os.path.join(os.path.dirname(__file__),
                                      "bench_kernels.py")],
        capture_output=True, text=True, timeout=900)
    if r.returncode != 0:
        # A crashed microbench must not read as a measurement.
        print(json.dumps({
            "metric": "paged_vs_slot", "error":
            f"bench_kernels rc={r.returncode}: "
            f"{r.stderr.strip().splitlines()[-1][-300:] if r.stderr.strip() else ''}",
        }), flush=True)
        return
    line = (r.stdout.strip().splitlines() or ["{}"])[-1]
    print(line, flush=True)


def bench_lane_padding(trials: int = 5) -> None:
    """Section 2: d=64 decode — padded Pallas (stored at 128 lanes) vs the
    unpadded XLA fallback, fused K-step L-layer loop at qwen2.5-0.5b-ish
    shapes (L24, Hkv2, G7, d64), b192 s1024 int8 KV."""
    from arks_tpu.ops.attention import decode_update_and_attend

    L, B, Hkv, G, S, D, K = 24, 192, 2, 7, 1024, 64, 32
    if os.environ.get("BD_SMOKE") == "1":  # CPU plumbing check only
        L, B, S, K, trials = 2, 16, 256, 2, 1
    key = jax.random.PRNGKey(0)
    ks = jax.random.split(key, 6)
    q = jax.random.normal(ks[0], (B, Hkv * G, D), jnp.bfloat16)
    kn = jax.random.normal(ks[1], (B, Hkv, D), jnp.bfloat16)
    vn = jax.random.normal(ks[2], (B, Hkv, D), jnp.bfloat16)
    lengths = (jnp.arange(B, dtype=jnp.int32) * 37) % (S - K - 1) + 1

    def mk_cache(d_store):
        kc = jax.random.randint(ks[3], (L, B, Hkv, S, d_store), -127, 128,
                                jnp.int8)
        vc = jax.random.randint(ks[4], (L, B, Hkv, S, d_store), -127, 128,
                                jnp.int8)
        if d_store != D:  # padded lanes hold zeros in real caches
            lane = jnp.arange(d_store) < D
            kc = jnp.where(lane, kc, 0)
            vc = jnp.where(lane, vc, 0)
        sc = jax.random.uniform(ks[5], (L, B, Hkv, S), jnp.float32,
                                0.01, 0.03)
        return kc, vc, sc, sc

    def loop(impl, kc, vc, kscale, vscale, lens):
        def step(carry, _):
            kc, vc, ksc, vsc, lens = carry
            def layer(carry2, lyr):
                kc, vc, ksc, vsc = carry2
                out, kc, vc, ksc, vsc = decode_update_and_attend(
                    q, kn, vn, kc, vc, lens, lyr, impl=impl,
                    k_scale=ksc, v_scale=vsc)
                return (kc, vc, ksc, vsc), out[:, 0, 0]
            (kc, vc, ksc, vsc), outs = jax.lax.scan(
                layer, (kc, vc, ksc, vsc),
                jnp.arange(L, dtype=jnp.int32))
            return (kc, vc, ksc, vsc, lens + 1), outs[-1]
        (kc, vc, ksc, vsc, lens), outs = jax.lax.scan(
            step, (kc, vc, kscale, vscale, lens), None, length=K)
        return outs

    res = {}
    for name, impl, d_store in (("pallas_padded", "pallas", 128),
                                ("xla_unpadded", "xla", D)):
        kc, vc, ksc, vsc = mk_cache(d_store)
        fn = jax.jit(functools.partial(loop, impl))
        sec = _best(lambda: fn(kc, vc, ksc, vsc, lengths), trials)
        res[f"{name}_s"] = round(sec, 4)
    res.update({
        "metric": "lane_padding_decode_d64_L24_b192_s1024_int8",
        "unit": "s per 32-step loop",
        "padded_vs_xla": round(res["pallas_padded_s"]
                               / res["xla_unpadded_s"], 3),
        "backend": jax.default_backend(),
    })
    print(json.dumps(res), flush=True)


def bench_moe_kernel(trials: int = 5) -> None:
    """Section 3: the expert-sorted grouped FFN — Pallas block-sparse
    kernel vs ragged_dot — at Mixtral-8x7B prefill shapes (bf16 weights;
    the kernel's fused-int8-dequant edge would only widen the gap)."""
    from arks_tpu.models import get_config
    from arks_tpu.models.moe import router_topk
    from arks_tpu.ops.moe_kernel import grouped_ffn

    smoke = os.environ.get("BD_SMOKE") == "1"
    cfg = get_config("tiny-mixtral" if smoke else "mixtral-8x7b")
    E, I, X = cfg.hidden_size, cfg.intermediate_size, cfg.num_experts
    k = cfg.num_experts_per_tok
    T = int(os.environ.get("MB_TOKENS", "256" if smoke else "4096"))
    if smoke:
        trials = 1
    t_start = time.perf_counter()

    def stage(msg: str) -> None:
        # Stage evidence on stderr: a tunnel that dies mid-run leaves a
        # trail of WHERE instead of a bare timeout.
        print(f"# moe: {msg} at {time.perf_counter() - t_start:.0f}s",
              file=sys.stderr, flush=True)

    key = jax.random.PRNGKey(1)
    ks = jax.random.split(key, 6)
    scale = 0.02
    # One jitted program materializes all ~2.8GB of weights: eager op-by-op
    # generation makes many round trips on a tunneled device.
    @jax.jit
    def init(ks):
        return (jax.random.normal(ks[0], (T, E), jnp.bfloat16) * scale,
                jax.random.normal(ks[1], (E, X), jnp.bfloat16) * scale,
                jax.random.normal(ks[2], (X, E, I), jnp.bfloat16) * scale,
                jax.random.normal(ks[3], (X, E, I), jnp.bfloat16) * scale,
                jax.random.normal(ks[4], (X, I, E), jnp.bfloat16) * scale)

    x, router, w_gate, w_up, w_down = init(ks)
    jax.block_until_ready(w_down)
    stage("weights ready")

    # Weights are jit ARGUMENTS, not closure captures: captured they bake
    # ~2.8GB of constants into the HLO, which the tunneled compile path
    # re-uploads per program (the r04 run timed out exactly here).
    def route(x, router):
        logits = jnp.einsum("te,ex->tx", x, router)
        vals, idx = router_topk(logits, cfg)
        flat = idx.reshape(-1)
        order = jnp.argsort(flat)
        xs = jnp.take(x, order // k, axis=0)
        return xs, jnp.take(flat, order), jnp.bincount(flat, length=X)

    def run_pallas(x, router, w_gate, w_up, w_down):
        xs, sorted_e, sizes = route(x, router)
        return grouped_ffn(xs, sorted_e, sizes, w_gate, w_up, w_down,
                           x.dtype)

    def run_ragged(x, router, w_gate, w_up, w_down):
        xs, sorted_e, sizes = route(x, router)
        gate = jax.lax.ragged_dot(xs, w_gate, sizes)
        up = jax.lax.ragged_dot(xs, w_up, sizes)
        act = jax.nn.silu(gate.astype(jnp.float32)).astype(gate.dtype) * up
        return jax.lax.ragged_dot(act, w_down, sizes)

    res = {}
    for name, fn in (("pallas", run_pallas), ("ragged_dot", run_ragged)):
        jf = jax.jit(fn)
        res[f"{name}_s"] = round(
            _best(lambda: jf(x, router, w_gate, w_up, w_down), trials), 4)
        stage(f"{name} measured")
    res.update({
        "metric": f"moe_grouped_ffn_mixtral8x7b_T{T}_bf16",
        "unit": "s per grouped FFN",
        "pallas_vs_ragged": round(res["pallas_s"] / res["ragged_dot_s"], 3),
        "backend": jax.default_backend(),
    })
    print(json.dumps(res), flush=True)


def main() -> None:
    only = os.environ.get("BD_ONLY", "")
    if only not in ("", "paged", "pad", "moe"):
        raise SystemExit(f"BD_ONLY={only!r}: expected paged|pad|moe (or "
                         "unset for all sections)")
    if not only or only == "paged":
        bench_paged_vs_slot()
    if not only or only == "pad":
        bench_lane_padding()
    if not only or only == "moe":
        bench_moe_kernel()


if __name__ == "__main__":
    main()
